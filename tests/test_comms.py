"""The fault-tolerant comms subsystem (``dpgo_tpu.comms``): wire protocol
(frame cap, incremental assembly), seeded fault injection, loopback + TCP
transports, the reliable channel (retry/backoff, sequence numbers, stale
and corrupt drops, heartbeats), the round bus with graceful agent dropout,
and the obs instrumentation incl. the zero-overhead telemetry-off fence."""

import socket
import struct
import time

import numpy as np
import pytest

from dpgo_tpu import obs
from dpgo_tpu.comms import (FaultInjector, FaultSpec,
                            LoopbackTransport, ProtocolError,
                            ReliableChannel, RetryPolicy,
                            TcpTransport, Transport, TransportClosed,
                            TransportTimeout, loopback_fleet)
from dpgo_tpu.comms.protocol import (HEADER, FrameAssembler, decode_payload,
                                     encode_frame, encode_payload,
                                     recv_frame, send_frame)
from dpgo_tpu.obs import run as obs_run_mod
from dpgo_tpu.obs.events import EventStream, read_events
from dpgo_tpu.obs import metrics as obs_metrics_mod


@pytest.fixture(autouse=True)
def _no_leaked_ambient_run():
    obs.end_run()
    yield
    obs.end_run()


FAST = RetryPolicy(max_attempts=3, base_delay_s=0.005, max_delay_s=0.02,
                   send_timeout_s=1.0, recv_timeout_s=1.0)


# ---------------------------------------------------------------------------
# Protocol
# ---------------------------------------------------------------------------

def test_payload_roundtrip_and_corrupt_rejection():
    arrays = {"a": np.arange(5), "b": np.eye(3)}
    data = encode_payload(arrays)
    out = decode_payload(data)
    assert out["a"].tolist() == [0, 1, 2, 3, 4]
    np.testing.assert_array_equal(out["b"], np.eye(3))
    # Bit-flipped archives raise ProtocolError, not random zipfile errors.
    bad = bytearray(data)
    for k in (1, len(bad) // 2, len(bad) - 2):
        bad[k] ^= 0xFF
    with pytest.raises(ProtocolError):
        decode_payload(bytes(bad))


def test_frame_assembler_incremental_and_cap():
    fa = FrameAssembler(max_frame_bytes=1 << 20)
    frame = encode_frame({"x": np.arange(10)})
    # Byte-at-a-time feeding (a recv deadline can strike anywhere).
    got = []
    for i in range(len(frame)):
        got += fa.feed(frame[i:i + 1])
    (payload,) = got
    assert decode_payload(payload)["x"].tolist() == list(range(10))
    assert fa.pending_bytes == 0
    # Two frames in one read.
    assert len(fa.feed(frame + frame)) == 2
    # An absurd length header dies cleanly instead of allocating 2**60.
    with pytest.raises(ProtocolError, match="cap"):
        fa.feed(struct.pack("<Q", 1 << 60))


def test_recv_frame_rejects_oversized_header():
    """The satellite fix: a corrupt/malicious 8-byte length prefix must
    raise ProtocolError before any allocation is sized from it."""
    a, b = socket.socketpair()
    try:
        a.sendall(struct.pack("<Q", 1 << 60) + b"junk")
        with pytest.raises(ProtocolError, match="cap"):
            recv_frame(b)
    finally:
        a.close()
        b.close()
    # Sane frames round-trip with the default cap (fresh stream — a raw
    # blocking socket has no reassembly to resynchronize after garbage;
    # that is TcpTransport's FrameAssembler job).
    a, b = socket.socketpair()
    try:
        send_frame(a, {"v": np.asarray([7.0])})
        assert recv_frame(b)["v"].tolist() == [7.0]
    finally:
        a.close()
        b.close()


# ---------------------------------------------------------------------------
# Fault injector
# ---------------------------------------------------------------------------

def test_fault_injector_is_deterministic_per_link():
    spec = FaultSpec(drop=0.3, delay=0.2, delay_s=(0.01, 0.02),
                     corrupt=0.1)
    data = b"x" * 64

    def decisions(seed):
        inj = FaultInjector(spec, seed=seed)
        return [tuple((d, bytes(p)) for d, p in inj.apply("a", "b", data))
                for _ in range(200)]

    assert decisions(7) == decisions(7)
    assert decisions(7) != decisions(8)
    # Per-link independence: interleaving another link's traffic does not
    # shift this link's stream.
    inj1, inj2 = FaultInjector(spec, seed=7), FaultInjector(spec, seed=7)
    out1 = [inj1.apply("a", "b", data) for _ in range(50)]
    out2 = []
    for _ in range(50):
        inj2.apply("c", "d", data)
        out2.append(inj2.apply("a", "b", data))
    assert [[(d, bytes(p)) for d, p in o] for o in out1] == \
        [[(d, bytes(p)) for d, p in o] for o in out2]


def test_fault_injector_modes():
    # Drop everything.
    inj = FaultInjector(FaultSpec(drop=1.0), seed=0)
    assert inj.apply("a", "b", b"data") == []
    assert inj.stats["dropped"] == 1
    # Partition: a<->b cut, a<->c free.
    inj = FaultInjector(FaultSpec(partitions=(("a",),)), seed=0)
    assert inj.apply("a", "b", b"d") == []
    assert inj.partitioned("b", "a")
    assert not inj.partitioned("b", "c")
    # Reorder: first held, released behind the second (newer first).
    inj = FaultInjector(FaultSpec(reorder=1.0), seed=0)
    assert inj.apply("a", "b", b"one") == []
    out = inj.apply("a", "b", b"two")
    assert [p for _, p in out] == [b"two", b"one"]
    # Disabled: pure passthrough regardless of spec.
    inj = FaultInjector(FaultSpec(drop=1.0), seed=0)
    inj.enabled = False
    assert inj.apply("a", "b", b"d") == [(0.0, b"d")]


# ---------------------------------------------------------------------------
# Transports
# ---------------------------------------------------------------------------

def test_loopback_transport_deadline_and_close():
    a, b = LoopbackTransport.pair()
    a.send({"v": np.asarray(1)})
    assert int(b.recv(timeout=1.0)["v"]) == 1
    t0 = time.monotonic()
    with pytest.raises(TransportTimeout):
        b.recv(timeout=0.05)
    assert time.monotonic() - t0 < 1.0
    a.close()
    with pytest.raises(TransportClosed):
        b.recv(timeout=1.0)


def test_loopback_delay_fault_delivers_late():
    inj = FaultInjector(FaultSpec(delay=1.0, delay_s=(0.08, 0.1)), seed=0)
    a, b = LoopbackTransport.pair(injector=inj)
    a.send({"v": np.asarray(1)})
    with pytest.raises(TransportTimeout):
        b.recv(timeout=0.01)  # not there yet
    assert int(b.recv(timeout=1.0)["v"]) == 1  # arrives once due


def _tcp_pair(**kw):
    a, b = socket.socketpair()
    return TcpTransport(a, src="a", dst="b", **kw), \
        TcpTransport(b, src="b", dst="a", **kw)


def test_tcp_transport_roundtrip_deadline_resume_and_close():
    ta, tb = _tcp_pair()
    try:
        ta.send({"v": np.arange(4)})
        assert tb.recv(timeout=1.0)["v"].tolist() == [0, 1, 2, 3]
        # Deadline strikes mid-frame: the partial bytes stay buffered and
        # the next recv resumes the same frame — no stream desync.
        frame = encode_frame({"w": np.arange(8)})
        ta._sock.sendall(HEADER.pack(len(frame) - HEADER.size))
        ta._sock.sendall(frame[HEADER.size:HEADER.size + 5])
        with pytest.raises(TransportTimeout):
            tb.recv(timeout=0.05)
        ta._sock.sendall(frame[HEADER.size + 5:])
        assert tb.recv(timeout=1.0)["w"].tolist() == list(range(8))
        ta.close()
        with pytest.raises(TransportClosed):
            tb.recv(timeout=1.0)
    finally:
        ta.close()
        tb.close()


def test_tcp_transport_oversized_header_raises():
    ta, tb = _tcp_pair(max_frame_bytes=1024)
    try:
        ta._sock.sendall(struct.pack("<Q", 1 << 40))
        with pytest.raises(ProtocolError, match="cap"):
            tb.recv(timeout=1.0)
        with pytest.raises(ProtocolError, match="cap"):
            ta.send({"big": np.zeros(4096)})  # send-side cap too
    finally:
        ta.close()
        tb.close()


# ---------------------------------------------------------------------------
# Reliable channel
# ---------------------------------------------------------------------------

class _FlakySendTransport(Transport):
    """Times out the first ``fails`` sends, then succeeds."""

    def __init__(self, fails):
        super().__init__("a", "b")
        self.fails = fails
        self.sent = []

    def send(self, arrays, timeout=None):
        if self.fails:
            self.fails -= 1
            raise TransportTimeout("injected")
        self.sent.append(arrays)
        return 1

    def recv(self, timeout=None):
        raise TransportTimeout("nothing")

    def close(self):
        pass


def test_send_retries_with_backoff_then_succeeds():
    ch = ReliableChannel(_FlakySendTransport(2), "flaky", FAST)
    ch.send({"v": np.asarray(1)})
    assert len(ch.transport.sent) == 1
    assert ch.totals.retries == 2
    assert ch.totals.timeouts == 2
    assert ch.totals.messages_sent == 1


def test_send_gives_up_after_max_attempts():
    ch = ReliableChannel(_FlakySendTransport(99), "dead", FAST)
    with pytest.raises(TransportTimeout):
        ch.send({"v": np.asarray(1)})
    assert ch.totals.retries == FAST.max_attempts - 1
    assert ch.totals.messages_sent == 0


def _channel_pair(injector=None, policy=FAST):
    a, b = LoopbackTransport.pair(injector=injector)
    return ReliableChannel(a, "a->b", policy), \
        ReliableChannel(b, "b->a", policy)


def test_sequence_numbers_drop_stale_and_reordered():
    inj = FaultInjector(FaultSpec(reorder=1.0), seed=0)
    ca, cb = _channel_pair(injector=inj)
    ca.send({"i": np.asarray(1)})  # held by the injector
    ca.send({"i": np.asarray(2)})  # released as [2, then 1]
    assert int(cb.recv(timeout=1.0)["i"]) == 2
    with pytest.raises(TransportTimeout):
        cb.recv(timeout=0.05)  # the late 1 was dropped as stale
    assert cb.totals.stale_dropped == 1
    assert cb.last_recv_seq == 1  # channel seq of the frame carrying i=2


def test_corrupt_frames_are_counted_and_skipped():
    inj = FaultInjector(FaultSpec(corrupt=1.0), seed=0)
    ca, cb = _channel_pair(injector=inj)
    ca.send({"i": np.asarray(1)})
    inj.enabled = False
    ca.send({"i": np.asarray(2)})
    assert int(cb.recv(timeout=1.0)["i"]) == 2
    assert cb.totals.corrupt_dropped == 1


def test_heartbeat_liveness():
    ca, cb = _channel_pair()
    assert cb.last_seen_age() is None
    ca.start_heartbeat(0.02)
    deadline = time.monotonic() + 2.0
    while cb.last_seen_age() is None and time.monotonic() < deadline:
        with pytest.raises(TransportTimeout):
            cb.recv(timeout=0.05)
    age = cb.last_seen_age()
    assert age is not None and age < 1.0
    assert cb.totals.heartbeats_received >= 1
    ca.close()
    cb.close()


def test_run_summary_and_counters_with_telemetry_on(tmp_path):
    inj = FaultInjector(FaultSpec(reorder=1.0), seed=0)
    with obs.run_scope(str(tmp_path / "run")) as run:
        ca, cb = _channel_pair(injector=inj)
        ca.send({"i": np.asarray(1)})
        ca.send({"i": np.asarray(2)})
        cb.recv(timeout=1.0)
        with pytest.raises(TransportTimeout):
            cb.recv(timeout=0.05)
        snap_counter = run.registry.counter("comms_stale_dropped").value(
            channel="b->a")
        ca.close()
        cb.close()
    evs = read_events(str(tmp_path / "run" / "events.jsonl"))
    summaries = {e["channel"]: e for e in evs
                 if e["event"] == "run_summary"
                 and e.get("channel") != "config"}  # fingerprint rides too
    assert set(summaries) == {"a->b", "b->a"}
    # The transport stamped its wire format into the config fingerprint.
    configs = [e for e in evs if e.get("channel") == "config"]
    assert configs and configs[-1]["fingerprint"]["wire_format"]
    assert summaries["a->b"]["messages_sent"] == 2
    assert summaries["b->a"]["messages_received"] == 1
    assert summaries["b->a"]["stale_dropped"] == 1
    assert summaries["b->a"]["timeouts"] == 1
    assert snap_counter == 1


# ---------------------------------------------------------------------------
# Round bus + graceful dropout
# ---------------------------------------------------------------------------

def _fleet(n=3, **kw):
    kw.setdefault("policy", FAST)
    kw.setdefault("round_timeout_s", 0.2)
    kw.setdefault("liveness_timeout_s", 0.15)
    return loopback_fleet(n, **kw)


def test_round_bus_merges_and_broadcasts():
    bus, clients = _fleet(3)
    for rid, c in clients.items():
        c.publish({"v": np.asarray(rid * 10)})
    merged = bus.round()
    assert {k for k in merged if k.endswith("|v")} == \
        {"r0|v", "r1|v", "r2|v"}
    for rid, c in clients.items():
        got = c.collect(timeout=1.0)
        peers = c.peer_frames(got)
        assert set(peers) == {0, 1, 2} - {rid}
        for p, pf in peers.items():
            assert int(pf["v"]) == p * 10
            assert int(pf["_pseq"]) >= 0
    assert bus.lost == set()
    bus.close()


def test_round_bus_detects_closed_robot_and_continues():
    bus, clients = _fleet(3)
    for c in clients.values():
        c.publish({"v": np.asarray(1)})
    bus.round()
    clients[1].close()  # robot 1 dies
    for rid in (0, 2):
        clients[rid].collect(timeout=1.0)
        clients[rid].publish({"v": np.asarray(2)})
    bus.round()
    assert bus.lost == {1}
    for rid in (0, 2):
        merged = clients[rid].collect(timeout=1.0)
        assert merged is not None
        assert clients[rid].lost == {1}
        assert not any(k.startswith("r1|") for k in merged)
    bus.close()


def test_round_bus_declares_silent_robot_lost_by_heartbeat():
    bus, clients = _fleet(2, miss_limit=2)
    clients[0].channel.start_heartbeat(0.02)  # robot 0 stays alive, mute-ish
    for c in clients.values():
        c.publish({"v": np.asarray(1)})
    bus.round()
    # Robot 1 goes silent WITHOUT closing: no frames, no heartbeat.  Robot 0
    # keeps publishing.  After miss_limit rounds with a stale heartbeat the
    # bus declares robot 1 lost; robot 0 (fresh heartbeat) is kept even when
    # its *data* frames miss a round.
    for _ in range(3):
        clients[0].collect(timeout=1.0)
        clients[0].publish({"v": np.asarray(2)})
        bus.round()
        if bus.lost:
            break
    assert bus.lost == {1}
    clients[0].collect(timeout=1.0)
    assert clients[0].lost == {1}
    bus.close()


def test_bus_serve_stops_when_everyone_is_gone():
    bus, clients = _fleet(2, round_timeout_s=0.05)
    for c in clients.values():
        c.close()
    t0 = time.monotonic()
    bus.serve(10_000)  # must return promptly, not spin 10k timeouts
    assert time.monotonic() - t0 < 5.0
    assert bus.lost == {0, 1}
    bus.close()


def test_bus_emits_peer_lost_event_and_aggregated_summary(tmp_path):
    with obs.run_scope(str(tmp_path / "run")):
        bus, clients = _fleet(2)
        for c in clients.values():
            c.publish({"v": np.asarray(1)})
        bus.round()
        clients[1].close()
        clients[0].collect(timeout=1.0)
        clients[0].publish({"v": np.asarray(2)})
        bus.round()
        bus.close()
        clients[0].close()
    evs = read_events(str(tmp_path / "run" / "events.jsonl"))
    (lost_ev,) = [e for e in evs if e["event"] == "peer_lost"]
    assert lost_ev["peer"] == 1 and lost_ev["reason"] == "closed"
    (bus_summary,) = [e for e in evs if e["event"] == "run_summary"
                      and e["channel"] == "bus"]
    assert bus_summary["peers_lost"] == [1]
    assert bus_summary["rounds_served"] == 2
    assert bus_summary["messages_received"] >= 3


def test_report_cli_shows_network_health(tmp_path, capsys):
    from dpgo_tpu.obs.report import main as report_main

    d = str(tmp_path / "run")
    with obs.run_scope(d):
        bus, clients = _fleet(2)
        for c in clients.values():
            c.publish({"v": np.asarray(1)})
        bus.round()
        clients[1].close()
        clients[0].collect(timeout=1.0)
        clients[0].publish({"v": np.asarray(2)})
        bus.round()
        bus.close()
        clients[0].close()
    assert report_main([d]) == 0
    out = capsys.readouterr().out
    assert "network health (comms):" in out
    assert "peers lost [1]" in out
    assert "peer_lost: bus lost peer 1 (closed)" in out


# ---------------------------------------------------------------------------
# The zero-overhead telemetry-off contract for the comms layer
# ---------------------------------------------------------------------------

def test_comms_telemetry_off_emits_zero_obs_events(monkeypatch):
    """Same fence-throw pattern as PR 1: with no ambient run, a faulty
    exchange — retries, stale drops, corrupt drops, a dead peer, channel
    close — must emit ZERO events, make ZERO registry calls, and perform
    ZERO obs-owned transfers.  Plain-int ChannelTotals still count."""

    def boom(*a, **kw):
        raise AssertionError("telemetry path taken while disabled")

    monkeypatch.setattr(EventStream, "emit", boom)
    monkeypatch.setattr(obs_run_mod, "materialize", boom)
    monkeypatch.setattr(obs, "materialize", boom)
    monkeypatch.setattr(obs_metrics_mod.Counter, "inc", boom)
    monkeypatch.setattr(obs_metrics_mod.Gauge, "set", boom)
    monkeypatch.setattr(obs_metrics_mod.Histogram, "observe", boom)
    monkeypatch.setattr(obs_metrics_mod.Histogram, "observe_many", boom)

    assert obs.get_run() is None
    inj = FaultInjector(FaultSpec(reorder=1.0, corrupt=0.2), seed=3)
    bus, clients = _fleet(3, injector=inj)
    for _ in range(4):
        for c in clients.values():
            c.publish({"v": np.asarray(1)})
        bus.round()
        for c in clients.values():
            c.collect(timeout=0.3)
    clients[2].close()
    for rid in (0, 1):
        clients[rid].publish({"v": np.asarray(2)})
    bus.round()
    assert bus.lost == {2}
    bus.close()
    for c in clients.values():
        c.close()
    # The always-on accounting still worked.
    totals = bus.totals()
    assert totals.messages_received > 0
    # Retry path too.
    ch = ReliableChannel(_FlakySendTransport(1), "flaky", FAST)
    ch.send({"v": np.asarray(1)})
    assert ch.totals.retries == 1
    ch.close()


def test_transport_frame_cap_constructor_validation():
    """The frame-size cap is a constructor knob on every transport (the
    serving front-end threads --max-frame-mb through it); a non-positive
    cap is a configuration error, caught at construction."""
    a, b = LoopbackTransport.pair(max_frame_bytes=512)
    try:
        assert a.max_frame_bytes == b.max_frame_bytes == 512
        with pytest.raises(ProtocolError, match="cap"):
            a.send({"big": np.zeros(4096)})
        a.send({"ok": np.zeros(4)})  # link still usable under the cap
        assert "ok" in b.recv(timeout=5)
    finally:
        a.close()
        b.close()
    with pytest.raises(ValueError, match="positive"):
        LoopbackTransport.pair(max_frame_bytes=0)
    with pytest.raises(ValueError, match="positive"):
        LoopbackTransport.pair(max_frame_bytes=-1)


def test_round_bus_admits_joiner_mid_run_and_broadcasts_joined(tmp_path):
    """The join handshake: a robot admitted mid-run via ``admit_hello``
    shows up in the relay from the next round, every client learns about
    it through the cumulative ``_joined`` broadcast key, and the hub emits
    a ``peer_joined`` event."""
    from dpgo_tpu.comms import BusClient

    with obs.run_scope(str(tmp_path / "join")):
        bus, clients = _fleet(2)
        for rid, c in clients.items():
            c.publish({"v": np.asarray(rid)})
        merged = bus.round()
        assert "_joined" not in merged  # nothing joined yet
        for c in clients.values():
            c.collect(timeout=1.0)

        t_bus, t_robot = LoopbackTransport.pair("bus", "robot2")
        hub_ch = ReliableChannel(t_bus, origin=-1)
        joiner = BusClient(ReliableChannel(t_robot, "robot2->bus", FAST), 2)
        joiner.hello()
        assert bus.admit_hello(hub_ch, timeout=1.0) == 2
        assert bus.joined == set()  # effective at the next round

        for rid, c in clients.items():
            c.publish({"v": np.asarray(rid)})
        joiner.publish({"v": np.asarray(2)})
        merged = bus.round()
        assert bus.joined == {2}
        assert "r2|v" in merged
        assert list(np.asarray(merged["_joined"])) == [2]
        for rid, c in clients.items():
            got = c.collect(timeout=1.0)
            assert c.joined == {2}
            assert set(c.peer_frames(got)) == {0, 1, 2} - {rid}
        got = joiner.collect(timeout=1.0)
        assert set(joiner.peer_frames(got)) == {0, 1}

        evs_dir = str(tmp_path / "join" / "events.jsonl")
        bus.close()
        for c in clients.values():
            c.close()
        joiner.close()
    evs = read_events(evs_dir)
    assert any(e["event"] == "peer_joined" and e.get("peer") == 2
               for e in evs)


def test_round_bus_readmission_revives_lost_robot():
    """Re-admitting a robot the hub declared lost clears its lost state
    and resumes gathering from it (the partition-heal rejoin path)."""
    bus, clients = _fleet(2)
    for rid, c in clients.items():
        c.publish({"v": np.asarray(rid)})
    bus.round()
    clients[1].close()
    clients[0].publish({"v": np.asarray(0)})
    bus.round()
    assert bus.lost == {1}

    from dpgo_tpu.comms import BusClient

    t_bus, t_robot = LoopbackTransport.pair("bus", "robot1")
    revived = BusClient(ReliableChannel(t_robot, "robot1->bus", FAST), 1)
    bus.admit(1, ReliableChannel(t_bus, origin=-1))
    clients[0].publish({"v": np.asarray(0)})
    revived.publish({"v": np.asarray(111)})
    merged = bus.round()
    assert bus.lost == set()
    assert int(np.asarray(merged["r1|v"])) == 111
    assert "_joined" in merged and list(np.asarray(merged["_joined"])) == [1]
    bus.close()
    clients[0].close()
    revived.close()


# ---------------------------------------------------------------------------
# connect_tcp: jittered-backoff dial budget (ISSUE 17)
# ---------------------------------------------------------------------------

def _unbound_port():
    """A port that was just free — nothing listens on it."""
    with socket.socket() as probe:
        probe.bind(("127.0.0.1", 0))
        return probe.getsockname()[1]


def test_connect_tcp_retries_until_listener_binds():
    """The out-of-process spawn race: the child's listener binds AFTER
    the parent starts dialing; the backoff budget must absorb it."""
    import threading

    from dpgo_tpu.comms.transport import connect_tcp

    port = _unbound_port()
    srv = socket.socket()
    srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    accepted = []

    def late_bind():
        time.sleep(0.25)
        srv.bind(("127.0.0.1", port))
        srv.listen(1)
        conn, _ = srv.accept()
        accepted.append(conn)

    t = threading.Thread(target=late_bind)
    t.start()
    try:
        sock = connect_tcp("127.0.0.1", port,
                           policy=RetryPolicy(base_delay_s=0.05,
                                              max_delay_s=0.2))
        sock.close()
    finally:
        t.join(timeout=10)
        for c in accepted:
            c.close()
        srv.close()
    assert accepted, "the late-bound listener never saw the dial"


def test_connect_tcp_exhausted_budget_raises_structured_error():
    from dpgo_tpu.comms.transport import ConnectError, connect_tcp

    port = _unbound_port()
    with pytest.raises(ConnectError) as ei:
        connect_tcp("127.0.0.1", port, attempts=3,
                    policy=RetryPolicy(base_delay_s=0.005,
                                       max_delay_s=0.02))
    e = ei.value
    assert isinstance(e, ConnectionError)  # callers catching the base see it
    assert e.host == "127.0.0.1" and e.port == port
    assert e.attempts == 3 and e.elapsed_s >= 0.0
    assert "3 connect attempts" in str(e)
    assert isinstance(e.__cause__, ConnectionError)


def test_connect_tcp_backoff_grows_exponentially_with_jitter(monkeypatch):
    from dpgo_tpu.comms import transport as transport_mod
    from dpgo_tpu.comms.transport import ConnectError, connect_tcp

    delays = []
    monkeypatch.setattr(transport_mod.time, "sleep",
                        lambda s: delays.append(s))
    with pytest.raises(ConnectError):
        connect_tcp("127.0.0.1", _unbound_port(), attempts=4,
                    policy=RetryPolicy(base_delay_s=0.1, max_delay_s=10.0,
                                       jitter=0.5),
                    rng=np.random.default_rng(0))
    # No sleep after the final (failed) attempt.
    assert len(delays) == 3
    for d, base in zip(delays, (0.1, 0.2, 0.4)):
        assert base <= d <= base * 1.5  # doubled base, bounded jitter
