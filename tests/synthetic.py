"""Test shim: synthetic pose-graph generation lives in the package now
(``dpgo_tpu.utils.synthetic``) so drivers and benchmarks can use it too."""

from dpgo_tpu.utils.synthetic import (  # noqa: F401
    make_measurements,
    random_rotation,
    random_trajectory,
    relative_measurement,
    trajectory_error,
)
