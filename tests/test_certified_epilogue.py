"""ISSUE 15 tests: the device-resident dual certificate and the fused
terminal epilogue.

The contract under test: with ``certify_mode="device"`` the certificate
payload rides the solve's ONE terminal blocking fetch (the verdict-word
cadence of 100/K host syncs per 100 rounds is unchanged), the f32 device
eigensolve never certifies alone outside its decidable band (REFUSE
falls back to the host f64 path), a decisively negative Rayleigh
quotient is a sound FAIL without f64, and the device lambda_min agrees
with the host dense/f64 eigensolves at pinned tolerance."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from dpgo_tpu.config import AgentParams
from dpgo_tpu.models import certify, local_pgo, rbcd
from dpgo_tpu.types import edge_set_from_measurements
from synthetic import make_measurements
from test_certify import dense_certificate


def _optimum(rng, n=12, num_lc=6):
    meas, _ = make_measurements(rng, n=n, d=3, num_lc=num_lc,
                                rot_noise=0.05, trans_noise=0.05)
    res = local_pgo.solve_local(meas, rank=5, grad_norm_tol=1e-9,
                                max_iters=500)
    return meas, res.X


def test_device_payload_matches_dense_and_host_f64(rng):
    """Parity pin: the gauge-deflated device LOBPCG's lambda_min agrees
    with the dense f64 eigensolve AND the host f64 LOBPCG within 1e-6 on
    a problem small enough to assemble, and its soundness probes hold
    (deflation basis near-kernel, RQ an upper bound on lambda_min)."""
    meas, X = _optimum(rng)
    edges = edge_set_from_measurements(meas, dtype=jnp.float64)
    S = dense_certificate(X, edges)
    lam_dense = float(np.linalg.eigvalsh(S)[0])

    payload = certify.device_certificate_payload(
        X, edges, jax.random.PRNGKey(0))
    lam_dev = float(payload["lam_min"])
    assert abs(lam_dev - lam_dense) < 1e-6 * max(1.0, abs(lam_dense))
    lam64, _, _ = certify.lambda_min_f64(np.asarray(X, np.float64), edges)
    assert abs(lam_dev - lam64) < 1e-6
    tol = 1e-5 * float(payload["wscale"])
    assert float(payload["defl_resid"]) <= 0.1 * tol
    assert float(payload["rq"]) >= lam_dense - 1e-9


def test_device_f64_accepts_and_wound_fails(rng):
    """An f64 device payload is decidable at the default eta: ACCEPT at
    the optimum, and a decisively wound configuration is a sound FAIL —
    both WITHOUT the host f64 fallback."""
    from dpgo_tpu.utils.synthetic import make_stitched_winding

    meas, X = _optimum(rng)
    edges = edge_set_from_measurements(meas, dtype=jnp.float64)
    payload = certify.device_certificate_payload(
        X, edges, jax.random.PRNGKey(0))
    eps = float(jnp.finfo(jnp.float64).eps)
    cert = certify.decide_device_certificate(payload, 1e-5, eps,
                                             f64_solve=None)
    assert cert.device_verdict == certify.CERT_ACCEPT
    assert cert.certified and cert.decidable
    assert cert.lambda_min_f64 is None  # f64 fallback never consulted

    measw, Xw = make_stitched_winding(3, 12)
    edgesw = edge_set_from_measurements(measw, dtype=jnp.float64)
    pw = certify.device_certificate_payload(
        jnp.asarray(Xw, jnp.float64), edgesw, jax.random.PRNGKey(0))
    certw = certify.decide_device_certificate(pw, 1e-5, eps, f64_solve=None)
    assert certw.device_verdict == certify.CERT_FAIL
    assert not certw.certified and certw.decidable


def test_f32_refuses_then_host_f64_decides(rng):
    """f32 never certifies alone at the default eta: the disagreement
    band is an explicit REFUSE, and providing the host f64 solve turns
    the same payload into a decided (certified) result."""
    meas, X = _optimum(rng)
    e32 = edge_set_from_measurements(meas, dtype=jnp.float32)
    X32 = jnp.asarray(X, jnp.float32)
    payload = certify.device_certificate_payload(
        X32, e32, jax.random.PRNGKey(0))
    eps = float(jnp.finfo(jnp.float32).eps)

    cert = certify.decide_device_certificate(payload, 1e-5, eps,
                                             f64_solve=None)
    assert cert.device_verdict == certify.CERT_REFUSE
    assert not cert.certified and not cert.decidable

    e64 = edge_set_from_measurements(meas, dtype=jnp.float64)
    solve = certify.host_f64_solve(np.asarray(X, np.float64), e64,
                                   tol_cert=cert.tol,
                                   warm=payload["direction"])
    cert64 = certify.decide_device_certificate(payload, 1e-5, eps,
                                               f64_solve=solve)
    assert cert64.device_verdict == certify.CERT_REFUSE  # f32 band stands
    assert cert64.certified and cert64.decidable         # f64 decided
    assert cert64.lambda_min_f64 is not None


def test_tiny_problem_probe_clamp(rng):
    """lobpcg_standard requires 5 * num_probe < dim; the payload clamps
    the probe count so micro problems (dim = n (d+1) = 16 here) trace
    and decide instead of crashing."""
    meas, _ = make_measurements(rng, n=4, d=3, num_lc=2,
                                rot_noise=0.01, trans_noise=0.01)
    res = local_pgo.solve_local(meas, rank=5, grad_norm_tol=1e-9,
                                max_iters=300)
    edges = edge_set_from_measurements(meas, dtype=jnp.float64)
    payload = certify.device_certificate_payload(
        res.X, edges, jax.random.PRNGKey(0), num_probe=4)
    for k in ("lam_min", "sigma", "defl_resid", "rq"):
        assert np.isfinite(float(payload[k])), k
    cert = certify.decide_device_certificate(
        payload, 1e-5, float(jnp.finfo(jnp.float64).eps))
    assert cert.device_verdict != certify.CERT_NONE


def test_certified_solve_single_terminal_fetch(rng, monkeypatch):
    """The acceptance pin: certify_mode="device" adds ZERO host syncs —
    the loop still performs rounds/K verdict-word fetches plus ONE fused
    terminal-epilogue fetch (the certificate rides it), so
    host_syncs_per_100_rounds stays 100/K with certification on."""
    meas, _ = make_measurements(rng, n=50, d=3, num_lc=25,
                                rot_noise=0.05, trans_noise=0.05)
    params = AgentParams(d=3, r=5, num_robots=2, rel_change_tol=0.0,
                         certify_mode="device")
    count = [0]
    orig = rbcd._host_fetch
    monkeypatch.setattr(rbcd, "_host_fetch",
                        lambda x: (count.__setitem__(0, count[0] + 1),
                                   orig(x))[1])
    res = rbcd.solve_rbcd(meas, 2, params=params, max_iters=32,
                          eval_every=4, grad_norm_tol=0.0,
                          dtype=jnp.float64, verdict_every=16)
    assert res.iterations == 32
    assert count[0] == 32 // 16 + 1  # words + one fused terminal epilogue
    cert = res.certificate
    assert cert is not None
    assert cert.device_verdict != certify.CERT_NONE
    # 32 f64 rounds land at the optimum on this instance rarely; the
    # decision just has to be SOUND (decided or refused, never a vacuous
    # accept at a non-stationary point).
    if cert.certified:
        assert cert.stationarity_gap < 1e-3


def test_certify_off_keeps_certificate_none(rng):
    """The default path is untouched: no certificate object, no change
    to the terminal fetch contents."""
    meas, _ = make_measurements(rng, n=24, d=3, num_lc=8,
                                rot_noise=0.05, trans_noise=0.05)
    params = AgentParams(d=3, r=5, num_robots=2)
    res = rbcd.solve_rbcd(meas, 2, params=params, max_iters=8,
                          eval_every=4, dtype=jnp.float64, verdict_every=4)
    assert res.certificate is None


def test_certified_solve_host_mode_certifies_at_optimum(rng):
    """certify_mode="host" (the legacy post-hoc sparse/f64 path) rides
    the same result field: a solve driven to tight gradient norm
    produces a decided, certified result with CERT_NONE as the device
    verdict (no device eigensolve ran)."""
    meas, _ = make_measurements(rng, n=20, d=3, num_lc=8,
                                rot_noise=0.05, trans_noise=0.05)
    # eta=1e-4: lambda_min at an RBCD terminal iterate carries a
    # -O(||rgrad||) term (~1e-3 at this instance's descent floor), so
    # the default eta=1e-5 honestly reads "not yet stationary".
    params = AgentParams(d=3, r=5, num_robots=2, certify_mode="host",
                         certify_eta=1e-4)
    res = rbcd.solve_rbcd(meas, 2, params=params, max_iters=300,
                          eval_every=5, grad_norm_tol=1e-8,
                          dtype=jnp.float64)
    cert = res.certificate
    assert cert is not None
    assert cert.device_verdict == certify.CERT_NONE
    assert cert.decidable and cert.certified
    assert cert.lambda_min >= -cert.tol
