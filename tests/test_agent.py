"""Tests for the per-robot ``PGOAgent`` message-passing runtime.

Mirrors the reference's test pyramid for the agent layer:
``testConstruction.cpp`` (constructor invariants), ``testLineGraph.cpp`` /
``testTriangleGraph.cpp`` (tiny-graph iterate), and
``testOptimizationThread.cpp`` (async thread lifecycle + solve-while-running),
plus an in-process multi-agent consensus solve playing the network the way
``examples/MultiRobotExample.cpp`` does.
"""

import threading
import time

import numpy as np
import pytest

from dpgo_tpu.agent import AgentState, PGOAgent
from dpgo_tpu.config import AgentParams, RobustCostParams, RobustCostType
from dpgo_tpu.utils.partition import agent_measurements, partition_contiguous
from dpgo_tpu.utils.synthetic import make_measurements


def make_agents(num_robots, n=12, num_lc=6, seed=0, d=3, r=5, **kw):
    rng = np.random.default_rng(seed)
    meas, T_true = make_measurements(rng, n=n, d=d, num_lc=num_lc,
                                     rot_noise=0.005, trans_noise=0.005)
    part = partition_contiguous(meas, num_robots)
    params = AgentParams(d=d, r=r, num_robots=num_robots, **kw)
    agents = [PGOAgent(a, params) for a in range(num_robots)]
    # Lifting-matrix broadcast from robot 0 (MultiRobotExample.cpp:139-146).
    for ag in agents[1:]:
        ag.set_lifting_matrix(agents[0].get_lifting_matrix())
    for ag in agents:
        ag.set_pose_graph(*agent_measurements(part, ag.robot_id))
    return agents, part, T_true


def exchange(agents, aux=False):
    """Play the network: all-to-all public pose push (the in-process loop of
    MultiRobotExample.cpp:186-213)."""
    dicts = [ag.get_shared_pose_dict() for ag in agents]
    for src in agents:
        for dst in agents:
            if src is not dst:
                dst.update_neighbor_poses(src.robot_id, dicts[src.robot_id])
    if aux:
        dicts = [ag.get_aux_shared_pose_dict() for ag in agents]
        for src in agents:
            for dst in agents:
                if src is not dst:
                    dst.update_aux_neighbor_poses(src.robot_id,
                                                  dicts[src.robot_id])
    for src in agents:
        st = src.get_status()
        for dst in agents:
            if src is not dst:
                dst.set_neighbor_status(st)


def broadcast_anchor(agents):
    anchor = agents[0].get_global_anchor()
    for ag in agents:
        ag.set_global_anchor(anchor)


def team_error(agents, part, T_true):
    """Max pose error of the assembled global trajectory vs ground truth
    (gauge-aligned at pose 0)."""
    from dpgo_tpu.utils.synthetic import trajectory_error

    broadcast_anchor(agents)
    Rs, ts = T_true
    N, d = Rs.shape[0], Rs.shape[1]
    T = np.zeros((N, d, d + 1))
    for a, ag in enumerate(agents):
        blk = ag.trajectory_in_global_frame()
        ids = part.global_index[a][part.global_index[a] >= 0]
        T[ids] = blk
    return trajectory_error(T, Rs, ts)


def test_construction():
    params = AgentParams(d=3, r=5, num_robots=2)
    ag = PGOAgent(0, params)
    assert ag.get_status().state == AgentState.WAIT_FOR_DATA
    assert ag.get_lifting_matrix().shape == (5, 3)
    ag1 = PGOAgent(1, params)
    with pytest.raises(AssertionError):
        ag1.get_lifting_matrix()  # only robot 0 self-generates


def test_single_robot_iterate_converges():
    agents, part, T_true = make_agents(1, n=8, num_lc=4)
    (ag,) = agents
    assert ag.get_status().state == AgentState.INITIALIZED
    for _ in range(10):
        ag.iterate(True)
    assert team_error(agents, part, T_true) < 1e-1


def test_distributed_initialization_and_consensus_solve():
    agents, part, T_true = make_agents(3, n=18, num_lc=12)
    # Robots 1, 2 wait for a pose message from an initialized neighbor.
    assert agents[0].get_status().state == AgentState.INITIALIZED
    assert agents[1].get_status().state == AgentState.WAIT_FOR_INITIALIZATION

    for it in range(120):
        exchange(agents)
        for ag in agents:
            ag.iterate(True)
        if all(ag.should_terminate() for ag in agents):
            break
    assert all(ag.get_status().state == AgentState.INITIALIZED
               for ag in agents)
    assert team_error(agents, part, T_true) < 1e-1


def test_early_publishing_uninitialized_neighbor_does_not_align():
    """On a status-gossiping transport, poses from a neighbor whose status
    has NOT arrived must not trigger frame alignment — an early-publishing
    transport could be shipping an uninitialized sender's garbage poses
    (the reference gates on gossiped ``mState``, ``PGOAgent.cpp:434-458``).
    """
    agents, part, T_true = make_agents(3, n=18, num_lc=12)
    a2 = agents[2]
    assert a2.get_status().state == AgentState.WAIT_FOR_INITIALIZATION
    # The transport gossips statuses (a2 holds robot 1's), but robot 0's
    # poses arrive before robot 0's status: no alignment.
    a2.set_neighbor_status(agents[1].get_status())
    a2.update_neighbor_poses(0, agents[0].get_shared_pose_dict())
    assert a2.get_status().state == AgentState.WAIT_FOR_INITIALIZATION
    # Once robot 0's INITIALIZED status lands, the next message aligns.
    a2.set_neighbor_status(agents[0].get_status())
    a2.update_neighbor_poses(0, agents[0].get_shared_pose_dict())
    assert a2.get_status().state == AgentState.INITIALIZED


def test_accelerated_solve():
    """Accelerated sync RBCD with the reference driver's sequencing
    (MultiRobotExample.cpp:175-217): non-selected agents iterate(false)
    [momentum bookkeeping], aux poses are exchanged, then the selected agent
    optimizes against the fresh aux poses."""
    agents, part, T_true = make_agents(2, n=12, num_lc=8, acceleration=True)
    for it in range(60):
        sel = it % len(agents)
        for a, ag in enumerate(agents):
            if a != sel:
                ag.iterate(False)
        exchange(agents, aux=True)
        agents[sel].iterate(True)
    assert team_error(agents, part, T_true) < 1e-1


def test_robust_solve_rejects_outliers():
    rng = np.random.default_rng(3)
    meas, T_true = make_measurements(rng, n=16, d=3, num_lc=10,
                                     rot_noise=0.005, trans_noise=0.005,
                                     outlier_lc=4)
    part = partition_contiguous(meas, 2)
    # The injected outliers are the last 4 rows; record their robot-local keys.
    pm = part.meas
    outlier_keys = {
        (int(pm.r1[k]), int(pm.p1[k]), int(pm.r2[k]), int(pm.p2[k]))
        for k in range(len(pm) - 4, len(pm))}
    params = AgentParams(
        d=3, r=5, num_robots=2,
        robust=RobustCostParams(cost_type=RobustCostType.GNC_TLS),
        robust_opt_inner_iters=10)
    agents = [PGOAgent(a, params) for a in range(2)]
    agents[1].set_lifting_matrix(agents[0].get_lifting_matrix())
    for ag in agents:
        ag.set_pose_graph(*agent_measurements(part, ag.robot_id))
    for it in range(120):
        exchange(agents)
        for ag in agents:
            ag.iterate(True)
        # Weight ownership: lower id computes, higher id receives.
        agents[1].update_shared_weights(agents[0].get_shared_weight_dict())
    assert team_error(agents, part, T_true) < 2e-1
    # GNC must have driven the injected outlier edges' weights to ~0.
    m0 = agents[0]._meas
    out_w = [agents[0]._weights[k] for k in range(len(m0))
             if (int(m0.r1[k]), int(m0.p1[k]), int(m0.r2[k]), int(m0.p2[k]))
             in outlier_keys]
    assert out_w and max(out_w) < 0.2


def test_weight_dict_ownership():
    agents, part, _ = make_agents(
        2, n=12, num_lc=8,
        robust=RobustCostParams(cost_type=RobustCostType.GNC_TLS))
    exchange(agents)
    w0 = agents[0].get_shared_weight_dict()
    w1 = agents[1].get_shared_weight_dict()
    assert len(w0) > 0          # robot 0 owns all its shared edges (1 > 0)
    assert len(w1) == 0         # robot 1 owns none
    agents[1].update_shared_weights({k: 0.25 for k in w0})
    # the received weights land on robot 1's copies of those edges
    m = agents[1]._meas
    got = [agents[1]._weights[k] for k in np.nonzero(agents[1]._is_shared)[0]]
    assert np.allclose(got, 0.25)


def test_thread_lifecycle():
    """Start/stop cycles (testOptimizationThread.cpp:10-27)."""
    agents, _, _ = make_agents(1, n=8, num_lc=4)
    (ag,) = agents
    for _ in range(3):
        ag.start_optimization_loop(rate_hz=50.0)
        assert ag.is_optimization_running()
        time.sleep(0.05)
        ag.end_optimization_loop()
        assert not ag.is_optimization_running()
    assert ag.get_status().iteration_number > 0


def test_async_solve_while_running():
    """Concurrent pose exchange while the loop runs
    (testOptimizationThread.cpp:29-89)."""
    agents, part, T_true = make_agents(2, n=12, num_lc=8)
    exchange(agents)
    for ag in agents:
        ag.start_optimization_loop(rate_hz=100.0)
    deadline = time.time() + 3.0
    while time.time() < deadline:
        exchange(agents)
        time.sleep(0.01)
    for ag in agents:
        ag.end_optimization_loop()
    assert team_error(agents, part, T_true) < 1e-1


def test_async_rejects_acceleration():
    agents, _, _ = make_agents(1, n=8, num_lc=4, acceleration=True)
    with pytest.raises(ValueError):
        agents[0].start_optimization_loop()


def test_frame_alignment_aborts_and_retries_on_incomplete_message():
    """``_try_initialize_in_global_frame``'s abort-and-retry contract
    (reference PGOAgent.cpp:396-400): a neighbor pose dict missing the
    required keys — empty, wrong pose ids, or arriving before the lifting
    matrix — must leave the agent in WAIT_FOR_INITIALIZATION, and the next
    complete message must succeed."""
    from dpgo_tpu.utils.synthetic import make_measurements as _mm

    rng = np.random.default_rng(1)
    meas, _ = _mm(rng, n=10, d=3, num_lc=5, rot_noise=0.005,
                  trans_noise=0.005)
    part = partition_contiguous(meas, 2)
    params = AgentParams(d=3, r=5, num_robots=2)
    a0 = PGOAgent(0, params)
    a1 = PGOAgent(1, params)  # deliberately NO lifting matrix yet
    a0.set_pose_graph(*agent_measurements(part, 0))
    a1.set_pose_graph(*agent_measurements(part, 1))
    assert a1.get_status().state == AgentState.WAIT_FOR_INITIALIZATION

    full = a0.get_shared_pose_dict()
    a1.set_neighbor_status(a0.get_status())

    # 1) Empty dict: no correspondence can be built -> abort, stay waiting.
    a1.update_neighbor_poses(0, {})
    assert a1.get_status().state == AgentState.WAIT_FOR_INITIALIZATION

    # 2) Wrong keys (pose ids this agent never references): same abort.
    bogus = {(0, 997 + k): blk for k, blk in enumerate(full.values())}
    a1.update_neighbor_poses(0, bogus)
    assert a1.get_status().state == AgentState.WAIT_FOR_INITIALIZATION

    # 3) Complete message but the lifting matrix has not arrived: defer.
    a1.update_neighbor_poses(0, full)
    assert a1.get_status().state == AgentState.WAIT_FOR_INITIALIZATION

    # 4) Lifting matrix lands, next complete message initializes.
    a1.set_lifting_matrix(a0.get_lifting_matrix())
    a1.update_neighbor_poses(0, a0.get_shared_pose_dict())
    assert a1.get_status().state == AgentState.INITIALIZED


def test_stale_pose_frames_are_dropped_by_sequence():
    """Transport sequence bookkeeping: a pose frame with a sequence at or
    below the last accepted one must not overwrite fresher cached poses
    (the reordered-network case the comms layer surfaces)."""
    agents, _, _ = make_agents(2, n=10, num_lc=4)
    a0, a1 = agents
    fresh = a0.get_shared_pose_dict()
    key = next(iter(fresh))
    a1.update_neighbor_poses(0, fresh, sequence=5)
    assert np.allclose(a1._neighbor_poses[key], fresh[key])
    stale = {k: np.zeros_like(v) for k, v in fresh.items()}
    a1.update_neighbor_poses(0, stale, sequence=5)   # duplicate
    a1.update_neighbor_poses(0, stale, sequence=3)   # reordered
    assert np.allclose(a1._neighbor_poses[key], fresh[key])
    a1.update_neighbor_poses(0, stale, sequence=6)   # genuinely newer
    assert np.allclose(a1._neighbor_poses[key], 0.0)
    # Sequence-less transports (in-process method calls) keep working.
    a1.update_neighbor_poses(0, fresh)
    assert np.allclose(a1._neighbor_poses[key], fresh[key])


def test_lost_neighbor_excluded_from_termination_quorum():
    """``mark_neighbor_lost`` removes a dead robot from the
    ``should_terminate`` quorum (sync-mode degradation), and a fresh pose
    message revives it."""
    # Huge tolerance: one stepped iterate makes an agent ready.
    agents, _, _ = make_agents(3, n=18, num_lc=12, rel_change_tol=1e9)
    for _ in range(2):
        exchange(agents)
    assert all(ag.get_status().state == AgentState.INITIALIZED
               for ag in agents)
    # Robots 0 and 1 step (become ready); robot 2 never iterates.
    agents[0].iterate(True)
    agents[1].iterate(True)
    exchange(agents)
    a0 = agents[0]
    assert a0.get_status().ready_to_terminate
    assert not a0.should_terminate()  # robot 2 is not ready -> no quorum
    a0.mark_neighbor_lost(2)
    assert a0.lost_neighbors == [2]
    assert a0.should_terminate()      # quorum over the survivors only
    # A fresh (sequence-stamped) message from robot 2 revives it.
    a0.update_neighbor_poses(2, agents[2].get_shared_pose_dict(),
                             sequence=0)
    assert a0.lost_neighbors == []
    assert not a0.should_terminate()


def test_reset_while_loop_running_does_not_deadlock():
    """reset() must join the loop thread without holding the agent lock."""
    agents, _, _ = make_agents(1, n=8, num_lc=4)
    (ag,) = agents
    ag.start_optimization_loop(rate_hz=200.0)
    time.sleep(0.1)
    done = []

    def do_reset():
        ag.reset()
        done.append(True)

    t = threading.Thread(target=do_reset, daemon=True)
    t.start()
    t.join(timeout=10.0)
    assert done, "reset() deadlocked against the optimization loop"
    assert not ag.is_optimization_running()


def test_weight_update_cap_honored():
    """robust_opt_num_weight_updates bounds GNC updates as in the batched
    core (models/rbcd.py)."""
    agents, _, _ = make_agents(
        1, n=8, num_lc=4,
        robust=RobustCostParams(cost_type=RobustCostType.GNC_TLS),
        robust_opt_inner_iters=2, robust_opt_num_weight_updates=3)
    (ag,) = agents
    for _ in range(20):
        ag.iterate(True)
    assert ag._num_weight_updates == 3


def test_pose_message_before_lifting_matrix_defers():
    """A pose message arriving before the lifting-matrix broadcast must not
    crash; initialization happens once the matrix arrives."""
    rng = np.random.default_rng(0)
    meas, _ = make_measurements(rng, n=12, d=3, num_lc=8,
                                rot_noise=0.005, trans_noise=0.005)
    part = partition_contiguous(meas, 2)
    params = AgentParams(d=3, r=5, num_robots=2)
    a0 = PGOAgent(0, params)
    a1 = PGOAgent(1, params)  # no lifting matrix yet
    a0.set_pose_graph(*agent_measurements(part, 0))
    a1.set_pose_graph(*agent_measurements(part, 1))
    a1.update_neighbor_poses(0, a0.get_shared_pose_dict())  # must not raise
    assert a1.get_status().state == AgentState.WAIT_FOR_INITIALIZATION
    a1.set_lifting_matrix(a0.get_lifting_matrix())
    a1.update_neighbor_poses(0, a0.get_shared_pose_dict())
    assert a1.get_status().state == AgentState.INITIALIZED


def test_log_data_dumps_on_reset_and_iter50(tmp_path):
    """logData wiring (reference PGOAgent.cpp:583-603, 646-651, 1301-1319):
    reset() writes measurements.csv / trajectory_optimized.csv / X.txt, the
    iteration-50 snapshot writes trajectory_early_stop.csv, log_trajectory()
    the per-robot-named files — and the CSVs round-trip through the
    loaders."""
    from dpgo_tpu.utils import logger as logger_mod

    agents, part, T_true = make_agents(
        2, n=10, num_lc=4, log_data=True, log_directory=str(tmp_path))
    exchange(agents)
    broadcast_anchor(agents)
    n0, n1 = agents[0].n, agents[1].n
    for it in range(51):
        exchange(agents)
        for ag in agents:
            ag.iterate(True)
    # Every robot dumps into its own subdirectory — shared AgentParams must
    # not make robots overwrite each other's fixed-name files.
    for rid in (0, 1):
        assert (tmp_path / f"robot{rid}" / "trajectory_early_stop.csv").exists()

    agents[0].log_trajectory()
    assert (tmp_path / "robot0" / "robot+0+trajectory_optimized.csv").exists()
    assert (tmp_path / "robot0" / "0_X.txt").exists()

    for ag in agents:
        ag.reset()
    for rid in (0, 1):
        for name in ("measurements.csv", "trajectory_optimized.csv", "X.txt"):
            assert (tmp_path / f"robot{rid}" / name).exists(), (rid, name)

    T = logger_mod.load_trajectory(
        str(tmp_path / "robot0" / "trajectory_optimized.csv"))
    assert T.shape == (n0, 3, 4)
    m = logger_mod.load_measurements(
        str(tmp_path / "robot0" / "measurements.csv"))
    assert len(m) > 0
    X = logger_mod.load_matrix(str(tmp_path / "robot0" / "X.txt"))
    assert X.shape == (5, 4 * n0)
    # Distinct content per robot: robot1's trajectory has robot1's length.
    T1 = logger_mod.load_trajectory(
        str(tmp_path / "robot1" / "trajectory_optimized.csv"))
    assert T1.shape == (n1, 3, 4)


def test_reset_rolls_instance():
    agents, part, _ = make_agents(1, n=8, num_lc=4)
    (ag,) = agents
    ylift = ag.get_lifting_matrix()
    ag.reset()
    st = ag.get_status()
    assert st.state == AgentState.WAIT_FOR_DATA
    assert st.instance_number == 1
    # Lifting matrix survives reset (PGOAgent.cpp:605-610).
    np.testing.assert_array_equal(ag.get_lifting_matrix(), ylift)
    ag.set_pose_graph(*agent_measurements(part, 0))
    assert ag.get_status().state == AgentState.INITIALIZED


def test_missing_neighbor_poses_skips_update():
    agents, _, _ = make_agents(2, n=12, num_lc=8)
    ag = agents[0]
    X_before = ag.X.copy()
    assert not ag.iterate(True)  # no neighbor poses cached yet -> skip
    np.testing.assert_array_equal(ag.X, X_before)


def test_fine_grained_pose_getters():
    """The reference's single-pose / neighbor-introspection surface
    (``PGOAgent.h:312-364``): get_neighbors, get_neighbor_public_poses,
    get_shared_pose(index), get_pose_in_global_frame,
    get_neighbor_pose_in_global_frame."""
    agents, part, _ = make_agents(3, n=15, num_lc=8)
    for _ in range(3):
        exchange(agents)
        for ag in agents:
            ag.iterate()
    exchange(agents)
    broadcast_anchor(agents)
    a0, a1 = agents[0], agents[1]

    # Neighbor introspection matches the shared-edge structure.
    nbrs = a0.get_neighbors()
    assert 1 in nbrs and 0 not in nbrs
    need = a0.get_neighbor_public_poses(1)
    assert need  # contiguous partitions always couple consecutive robots
    # ...and each advertised pose is eventually received: the cached
    # neighbor pose resolves in the global frame.
    anchor_ok = a0.get_neighbor_pose_in_global_frame(1, need[0])
    assert anchor_ok is not None and anchor_ok.shape == (3, 4)
    assert a0.get_neighbor_pose_in_global_frame(1, 10**6) is None

    # Indexed shared pose = the block the pose dict would carry.
    pd = a1.get_shared_pose_dict()
    (rid, p0), blk = next(iter(sorted(pd.items())))
    assert rid == 1
    np.testing.assert_allclose(a1.get_shared_pose(p0), blk)
    assert a1.get_shared_pose(a1.n) is None
    assert a1.get_shared_pose(-1) is None

    # Own pose in global frame: linear anchor map (no SO(d) projection),
    # consistent between the owner's view and a neighbor's cached view of
    # the same public pose (same exchanged block, same anchor).
    g_own = a1.get_pose_in_global_frame(p0)
    assert g_own is not None and g_own.shape == (3, 4)
    g_nbr = a0.get_neighbor_pose_in_global_frame(1, p0) \
        if (1, p0) in [(1, q) for q in a0.get_neighbor_public_poses(1)] \
        else None
    if g_nbr is not None:
        np.testing.assert_allclose(g_own, g_nbr, atol=1e-12)
    # Robot 0's pose 0 is the anchor itself: identity rotation, zero t.
    g00 = a0.get_pose_in_global_frame(0)
    np.testing.assert_allclose(g00[:, :3], np.eye(3), atol=1e-9)
    np.testing.assert_allclose(g00[:, 3], 0.0, atol=1e-9)


def test_aux_shared_pose_getter():
    agents, part, _ = make_agents(2, n=10, num_lc=4, acceleration=True)
    exchange(agents)
    for ag in agents:
        ag.iterate()
    a0 = agents[0]
    blk = a0.get_aux_shared_pose(0)
    assert blk is not None and blk.shape == (a0.r, a0.d + 1)
    assert a0.get_aux_shared_pose(a0.n) is None


def test_agent_iterate_pallas_kernel_matches_ell():
    """The deployment surface must run the SAME engine as the batched
    core: with pallas_tcg forced (interpreter mode off-TPU), each robot's
    ``iterate()`` routes through the fused VMEM kernel
    (``agent._pallas_tiles`` -> ``rtr_full_call``) and the trajectory must
    match the ELL-path agents to kernel-parity tolerance (the f32 kernel
    vs the f64 ELL path; VERDICT r3 weak item 8)."""
    from dpgo_tpu.config import SolverParams

    kw = dict(rel_change_tol=0.0)
    ag_k, part, _ = make_agents(
        2, n=10, num_lc=4,
        solver=SolverParams(pallas_tcg=True, grad_norm_tol=1e-9), **kw)
    ag_e, _, _ = make_agents(
        2, n=10, num_lc=4,
        solver=SolverParams(pallas_tcg=False, grad_norm_tol=1e-9), **kw)
    # The kernel path must actually be engaged, not silently skipped.
    assert ag_k[0]._pallas_tiles() is not None
    assert ag_e[0]._pallas_tiles() is None
    for it in range(4):
        exchange(ag_k)
        exchange(ag_e)
        for ag in ag_k:
            ag.iterate(True)
        for ag in ag_e:
            ag.iterate(True)
    for k, e in zip(ag_k, ag_e):
        assert np.allclose(k.X, e.X, atol=5e-5), \
            np.abs(k.X - e.X).max()


def test_status_fetch_every_latches_rel_change():
    """Deployment verdict cadence (AgentParams.status_fetch_every): with
    K > 1 and telemetry off, iterate() leaves the status scalar
    device-latched between fetch boundaries — the gossiped
    relative_change only refreshes every K iterates — and the solve
    still converges to the same place as the per-iterate fetch."""
    import math

    agents, part, _ = make_agents(2, status_fetch_every=3)
    ref_agents, _, _ = make_agents(2)

    def drive(ags, rounds):
        for i in range(rounds):
            exchange(ags)
            for ag in ags:
                ag.iterate()
            yield i + 1

    ref = drive(ref_agents, 6)
    for it in drive(agents, 6):
        next(ref)
        if it < 3:
            # Robot 1 steps from round 1 (robot 0's init frame arrived in
            # the first exchange) but, before the first K boundary, its
            # gossiped scalar still reads the initial inf — the value
            # never left the device.
            assert math.isinf(agents[1].get_status().relative_change)
        if it % 3 == 0:
            assert all(math.isfinite(ag.get_status().relative_change)
                       for ag in agents)
    # Identical math either way — only the fetch cadence differs.
    for a, b in zip(agents, ref_agents):
        np.testing.assert_allclose(np.asarray(a.X), np.asarray(b.X),
                                   rtol=0, atol=0)


def test_revived_neighbor_sequence_reset_and_cache_invalidation():
    """Lost/revive asymmetry fix: the FIRST frame from a revived neighbor
    wins regardless of its sequence number (the robot may have restarted
    its numbering), and the pre-outage cached poses are invalidated rather
    than merged — a pose the fresh frame does not resupply reads as
    missing, so the iterate skips instead of consuming stale state."""
    agents, _, _ = make_agents(2, n=10, num_lc=6)
    a0, a1 = agents
    fresh = a0.get_shared_pose_dict()
    assert len(fresh) >= 2  # the scenario needs a partial refill
    keys = sorted(fresh)
    a1.update_neighbor_poses(0, fresh, sequence=7)
    for k in keys:
        assert a1._nbr_lookup(k) is not None

    a1.mark_neighbor_lost(0)
    # Revival frame from a REBOOTED robot 0: lower sequence, and only one
    # of the public poses on board.
    partial = {keys[0]: np.ones_like(fresh[keys[0]])}
    a1.update_neighbor_poses(0, partial, sequence=2)
    assert a1.lost_neighbors == []
    np.testing.assert_allclose(a1._nbr_lookup(keys[0]), 1.0)
    for k in keys[1:]:
        assert a1._nbr_lookup(k) is None  # invalidated, NOT merged
    # The monotonic check resumes from the reset point.
    a1.update_neighbor_poses(0, {keys[0]: np.zeros_like(fresh[keys[0]])},
                             sequence=1)  # stale vs the reset seq 2
    np.testing.assert_allclose(a1._nbr_lookup(keys[0]), 1.0)
    a1.update_neighbor_poses(0, {keys[0]: np.zeros_like(fresh[keys[0]])},
                             sequence=3)
    np.testing.assert_allclose(a1._nbr_lookup(keys[0]), 0.0)


def test_admit_neighbor_extends_quorum_and_problem():
    """``admit_neighbor`` is the inverse of ``mark_neighbor_lost``: the
    joiner EXTENDS the consensus test (a 2-robot fleet that was ready to
    terminate is not ready once robot 2 joins until robot 2 is), and the
    admitted shared edges grow the live problem in place (edge rows,
    neighbor slots, public poses) with the iterate preserved."""
    from dpgo_tpu.utils.partition import (agent_measurements as _am,
                                          partition_contiguous as _pc)
    from dpgo_tpu.utils.synthetic import make_measurements as _mm

    rng = np.random.default_rng(3)
    meas, _ = _mm(rng, n=18, d=3, num_lc=10, rot_noise=0.01,
                  trans_noise=0.01)
    part3 = _pc(meas, 3)

    def drop_joiner(rid):
        odo, priv, shared = _am(part3, rid)
        touches = (np.asarray(shared.r1) == 2) | (np.asarray(shared.r2) == 2)
        return (odo, priv, shared.select(~touches)), shared.select(touches)

    params2 = AgentParams(d=3, r=5, num_robots=2, rel_change_tol=1e9)
    agents = {rid: PGOAgent(rid, params2) for rid in (0, 1)}
    agents[1].set_lifting_matrix(agents[0].get_lifting_matrix())
    withheld = {}
    for rid in (0, 1):
        kept, withheld[rid] = drop_joiner(rid)
        agents[rid].set_pose_graph(*kept)
    for _ in range(2):
        exchange(list(agents.values()))
    for ag in agents.values():
        ag.iterate(True)
    exchange(list(agents.values()))
    for ag in agents.values():
        ag.iterate(True)
    exchange(list(agents.values()))
    assert agents[0].should_terminate()

    e_before = {rid: int(agents[rid]._edges.i.shape[0]) for rid in (0, 1)}
    X_before = {rid: np.asarray(agents[rid].X).copy() for rid in (0, 1)}
    for rid in (0, 1):
        added = agents[rid].admit_neighbor(2, withheld[rid])
        assert added == len(withheld[rid])
        assert agents[rid].num_robots == 3
        assert int(agents[rid]._edges.i.shape[0]) == \
            e_before[rid] + len(withheld[rid])
        # the iterate survives the extension untouched
        np.testing.assert_array_equal(np.asarray(agents[rid].X),
                                      X_before[rid])
    # Consensus must re-form around the larger fleet: not ready now.
    assert not agents[0].should_terminate()

    # Bring robot 2 up and run the full fleet to readiness again.
    params3 = AgentParams(d=3, r=5, num_robots=3, rel_change_tol=1e9)
    a2 = PGOAgent(2, params3)
    a2.set_lifting_matrix(agents[0].get_lifting_matrix())
    a2.set_pose_graph(*_am(part3, 2))
    fleet = [agents[0], agents[1], a2]
    for _ in range(3):
        exchange(fleet)
        for ag in fleet:
            ag.iterate(True)
    exchange(fleet)
    assert agents[0].should_terminate()


def test_admit_neighbor_rejects_unknown_own_poses():
    import dataclasses as _dc

    agents, _, _ = make_agents(2, n=10, num_lc=4)
    a0 = agents[0]
    bad = _dc.replace(
        a0._meas.select([0]),
        r1=np.asarray([0], np.int32), p1=np.asarray([a0.n + 3], np.int64),
        r2=np.asarray([2], np.int32), p2=np.asarray([0], np.int64))
    with pytest.raises(ValueError, match="own poses"):
        a0.admit_neighbor(2, bad)
