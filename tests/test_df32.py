"""df32 (double-float32) arithmetic: accuracy against numpy float64.

These bounds pin the error-free transforms (two_sum / Dekker two_prod)
against compiler regressions: if XLA ever starts reassociating f32 adds
or contracting ``a*b - p`` into an fma on some backend, the measured
~1e-13 relative accuracy collapses to f32's ~1e-7 and these tests fail
loudly.  The on-device recenter (``models.refine_fused``) is built on
exactly these guarantees.
"""
import numpy as np


from dpgo_tpu.ops import df32


def _rand(n, lo=-8, hi=8, seed=7):
    rng = np.random.default_rng(seed)
    return rng.standard_normal(n) * np.exp(rng.uniform(lo, hi, n))


def _relerr(got64, ref64):
    return np.max(np.abs(got64 - ref64) / np.maximum(np.abs(ref64), 1e-300))


def test_from_f64_roundtrip():
    # df32 carries ~49 mantissa bits: the roundtrip is not bit-exact for
    # full f64 inputs, but must be ~2^-49 relative (vs f32's 2^-24).
    a = _rand(1000, seed=1)
    assert _relerr(df32.to_f64(df32.from_f64(a)), a) < 2.0 ** -48
    # f32-representable inputs ARE exact.
    a32 = a.astype(np.float32).astype(np.float64)
    assert np.array_equal(df32.to_f64(df32.from_f64(a32)), a32)


def test_add_mul_relative_accuracy():
    a, b = _rand(4096, seed=2), _rand(4096, seed=3)
    da, db = df32.from_f64(a), df32.from_f64(b)

    run = df32.precise_jit(
        lambda da, db: (df32.add(da, db), df32.mul(da, db)))

    s, p = run(da, db)
    # a, b are exactly representable (from_f64), so f64 is the truth.
    # Sums can cancel arbitrarily, so bound the ABSOLUTE error against
    # the df32 ulp of the larger operand instead of the relative error.
    s_ref, p_ref = a + b, a * b
    mag = np.maximum(np.abs(a), np.abs(b))
    assert np.max(np.abs(df32.to_f64(s) - s_ref) / mag) < 1e-13
    assert _relerr(df32.to_f64(p), p_ref) < 1e-13


def test_dot_and_fold_sum():
    a, b = _rand(5000, seed=4), _rand(5000, seed=5)
    da, db = df32.from_f64(a), df32.from_f64(b)
    d = df32.precise_jit(lambda x, y: df32.dot(x, y))(da, db)
    ref = float(np.sum(a * b))
    assert abs(df32.to_f64(d) - ref) / abs(ref) < 1e-12
    s = df32.precise_jit(lambda x: df32.fold_sum(x))(da)
    assert abs(df32.to_f64(s) - a.sum()) / max(abs(a.sum()), 1e-300) < 1e-11


def test_fold_sum_cancellation():
    """Catastrophic cancellation: +x and -x pairs plus a tiny residual —
    f32 loses it entirely, df32 keeps ~1e-9 relative."""
    x = _rand(512, 0, 6, seed=6)
    tiny = _rand(512, -14, -10, seed=8)
    seq = np.concatenate([x, -x, tiny])
    ref = seq.sum()  # == tiny.sum() up to f64 roundoff
    s = df32.to_f64(df32.precise_jit(df32.fold_sum)(df32.from_f64(seq)))
    f32_s = float(np.float32(seq.astype(np.float32).sum()))
    assert abs(s - ref) / abs(ref) < 1e-6
    assert abs(s - ref) < abs(f32_s - ref) / 100


def test_matmul_small():
    a = _rand(6 * 5 * 3, seed=9).reshape(6, 5, 3)
    b = _rand(6 * 3 * 4, seed=10).reshape(6, 3, 4)
    got = df32.to_f64(df32.precise_jit(df32.matmul_small)(
        df32.from_f64(a), df32.from_f64(b)))
    # Entries can cancel, so scale the error by the no-cancellation
    # magnitude sum |a| @ |b| (the backward-error yardstick).
    mag = np.abs(a) @ np.abs(b)
    assert np.max(np.abs(got - a @ b) / mag) < 1e-13


def test_div_sqrt():
    a = np.abs(_rand(2048, seed=11)) + 1e-6
    b = np.abs(_rand(2048, seed=12)) + 1e-6
    q = df32.to_f64(df32.precise_jit(df32.div)(df32.from_f64(a), df32.from_f64(b)))
    assert _relerr(q, a / b) < 1e-12
    r = df32.to_f64(df32.precise_jit(df32.sqrt)(df32.from_f64(a)))
    assert _relerr(r, np.sqrt(a)) < 1e-12


def test_sym_scale_sub():
    m = _rand(4 * 3 * 3, seed=13).reshape(4, 3, 3)
    s = df32.to_f64(df32.precise_jit(df32.sym)(df32.from_f64(m)))
    assert _relerr(s, 0.5 * (m + np.swapaxes(m, -1, -2))) < 1e-13
    d = df32.to_f64(df32.precise_jit(df32.sub)(df32.from_f64(m), df32.from_f64(m)))
    assert np.all(d == 0.0)
