"""Tests for the g2o reader against the reference datasets."""

import numpy as np

from dpgo_tpu.utils import g2o


def test_key_decoding_plain_ints():
    r, p = g2o.key_to_robot_keyframe(np.array([0, 5, 1000]))
    assert np.array_equal(r, [0, 0, 0])
    assert np.array_equal(p, [0, 5, 1000])


def test_key_decoding_robot_chars():
    # gtsam symbol: chr in the top byte, index in the low 48 bits.
    key = (np.uint64(ord("b")) << np.uint64(56)) | np.uint64(42)
    r, p = g2o.key_to_robot_keyframe(key)
    assert int(r) == ord("b")
    assert int(p) == 42


def test_read_small_grid(data_dir):
    m = g2o.read_g2o(f"{data_dir}/smallGrid3D.g2o")
    assert m.d == 3
    assert m.num_poses == 125
    assert len(m) == 297
    # Rotations must be valid.
    eye = np.broadcast_to(np.eye(3), (297, 3, 3))
    assert np.allclose(np.swapaxes(m.R, -1, -2) @ m.R, eye, atol=1e-6)
    assert np.all(m.kappa > 0)
    assert np.all(m.tau > 0)
    assert np.all(m.weight == 1.0)


def test_read_se2(data_dir):
    m = g2o.read_g2o(f"{data_dir}/kitti_00.g2o")
    assert m.d == 2
    # The file has no VERTEX lines; ids are contiguous 0..4540.
    assert m.num_poses == 4541
    assert len(m) == 4676
    eye = np.broadcast_to(np.eye(2), (len(m), 2, 2))
    assert np.allclose(np.swapaxes(m.R, -1, -2) @ m.R, eye, atol=1e-8)


def test_read_sphere2500(data_dir):
    m = g2o.read_g2o(f"{data_dir}/sphere2500.g2o")
    assert m.num_poses == 2500
    assert len(m) == 4949


def test_multi_robot_keys_parse_exactly(tmp_path):
    # gtsam symbol keys exceed 2^53; index bits must survive parsing.
    key_a = (ord("a") << 56) | 41
    key_b = (ord("b") << 56) | 42
    p = tmp_path / "mr.g2o"
    p.write_text(
        f"EDGE_SE2 {key_a} {key_b} 1.0 0.0 0.1 4.0 0.0 0.0 4.0 0.0 9.0\n"
    )
    m = g2o.read_g2o(str(p))
    assert int(m.r1[0]) == ord("a") and int(m.p1[0]) == 41
    assert int(m.r2[0]) == ord("b") and int(m.p2[0]) == 42


def test_se2_kappa_is_i33(data_dir, tmp_path):
    # For SE(2), kappa is taken directly from I33 (DPGO_utils.cpp:144).
    p = tmp_path / "tiny.g2o"
    p.write_text(
        "VERTEX_SE2 0 0 0 0\n"
        "VERTEX_SE2 1 1 0 0\n"
        "EDGE_SE2 0 1 1.0 0.0 0.1 4.0 0.0 0.0 4.0 0.0 9.0\n"
    )
    m = g2o.read_g2o(str(p))
    assert np.isclose(m.kappa[0], 9.0)
    # tau = 2 / tr(inv(diag(4,4))) = 2 / 0.5 = 4
    assert np.isclose(m.tau[0], 4.0)


def _synthetic_meas(n=20, d=3, seed=0):
    from dpgo_tpu.utils.synthetic import make_measurements

    meas, _ = make_measurements(np.random.default_rng(seed), n=n, d=d,
                                num_lc=4, rot_noise=0.01, trans_noise=0.01)
    return meas


def test_read_g2o_bytes_and_file_like_round_trip(tmp_path):
    """write_g2o -> read back as path, bytes, bytearray, and file-like
    (binary + text) — all five sources parse identically, so the serving
    plane can decode uploaded payloads without temp files."""
    import io

    for d in (2, 3):
        meas = _synthetic_meas(d=d, seed=d)
        path = str(tmp_path / f"rt_{d}.g2o")
        g2o.write_g2o(meas, path)
        with open(path, "rb") as fh:
            raw = fh.read()
        from_path = g2o.read_g2o(path)
        variants = [
            g2o.read_g2o(raw),
            g2o.read_g2o(bytearray(raw)),
            g2o.read_g2o(io.BytesIO(raw)),
            g2o.read_g2o(io.StringIO(raw.decode())),
        ]
        for m in variants:
            assert m.d == from_path.d == meas.d
            assert len(m) == len(from_path) == len(meas)
            np.testing.assert_array_equal(m.p1, from_path.p1)
            np.testing.assert_array_equal(m.p2, from_path.p2)
            np.testing.assert_allclose(m.R, from_path.R, atol=1e-12)
            np.testing.assert_allclose(m.t, from_path.t, atol=1e-12)
            np.testing.assert_allclose(m.kappa, from_path.kappa, atol=1e-9)
            np.testing.assert_allclose(m.tau, from_path.tau, atol=1e-9)
        # The write -> read cycle preserves the original measurements.
        np.testing.assert_allclose(from_path.R, meas.R, atol=1e-9)
        np.testing.assert_allclose(from_path.t, meas.t, atol=1e-9)


def test_read_g2o_native_backend_requires_path():
    import pytest

    with pytest.raises(ValueError, match="filesystem path"):
        g2o.read_g2o(b"EDGE_SE2 0 1 1 0 0 4 0 0 4 0 9\n", backend="native")


def test_read_g2o_bytes_no_edges_message():
    import pytest

    with pytest.raises(ValueError, match="No edges found in g2o source"):
        g2o.read_g2o(b"VERTEX_SE2 0 0 0 0\n")
