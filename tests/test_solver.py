"""Tests for the RTR / truncated-CG solver (replacing ROPTLIB RTRNewton)."""

import jax
import jax.numpy as jnp
import numpy as np

from dpgo_tpu.config import SolverParams
from dpgo_tpu.models.local_pgo import lift, make_problem, round_solution
from dpgo_tpu.ops import manifold, solver
from dpgo_tpu.types import edge_set_from_measurements
from dpgo_tpu.utils.lie import fixed_stiefel
from synthetic import make_measurements, trajectory_error


def setup_problem(rng, n=15, d=3, rank=5, **kw):
    meas, truth = make_measurements(rng, n=n, d=d, **kw)
    edges = edge_set_from_measurements(meas, dtype=jnp.float64)
    problem = make_problem(edges, n)
    return meas, edges, problem, truth


def test_tcg_solves_spd_newton_system(rng):
    # Validate the CG machinery itself on a synthetic SPD operator (the PGO
    # Hessian away from a critical point is generally indefinite — tCG's
    # negative-curvature exit there is by design and covered by the RTR
    # convergence tests).
    shape = (4, 3, 4)
    dim = int(np.prod(shape))
    B = rng.standard_normal((dim, dim))
    Hmat = 4.0 * np.eye(dim) + B @ B.T / dim
    g = jnp.asarray(rng.standard_normal(shape))
    X = jnp.zeros(shape, jnp.float64)  # unused by hvp/precond below

    hvp = lambda V: jnp.reshape(jnp.asarray(Hmat) @ jnp.reshape(V, (-1,)), shape)

    res = solver.truncated_cg(X, g, hvp, lambda V: V, jnp.asarray(1e9),
                              max_iters=200, kappa=1e-12, theta=1.0)
    assert not bool(res.hit_boundary)
    eta_exact = -np.linalg.solve(Hmat, np.asarray(g).reshape(-1)).reshape(shape)
    assert np.allclose(res.eta, eta_exact, atol=1e-8)
    # Heta bookkeeping must match H @ eta.
    assert np.allclose(res.heta, np.asarray(hvp(res.eta)), atol=1e-8)

    # Perfect preconditioner (M = H^{-1}): converges in one iteration.
    Hinv = np.linalg.inv(Hmat)
    pre = lambda V: jnp.reshape(jnp.asarray(Hinv) @ jnp.reshape(V, (-1,)), shape)
    res1 = solver.truncated_cg(X, g, hvp, pre, jnp.asarray(1e9), max_iters=200,
                               kappa=1e-10)
    assert int(res1.iters) <= 2
    assert np.allclose(res1.eta, eta_exact, atol=1e-8)

    # Small radius: the step must land on the boundary.
    res_b = solver.truncated_cg(X, g, hvp, lambda V: V, jnp.asarray(1e-3),
                                max_iters=200)
    assert bool(res_b.hit_boundary)
    assert np.isclose(float(manifold.norm(res_b.eta)), 1e-3, rtol=1e-9)


def test_tcg_on_pgo_model_decreases(rng):
    # On the real (possibly indefinite) PGO Hessian, tCG must return a step
    # with negative model value within the radius.
    meas, edges, problem, (Rs, ts) = setup_problem(rng, num_lc=8)
    ylift = jnp.eye(3, dtype=jnp.float64)
    X_opt = lift(jnp.asarray(np.concatenate([Rs, ts[..., None]], -1)), ylift)
    pert = 1e-2 * jax.random.normal(jax.random.PRNGKey(0), X_opt.shape, jnp.float64)
    X = manifold.project(X_opt + pert)

    eg = problem.egrad(X)
    g = manifold.rgrad(X, eg)
    hvp = lambda V: manifold.ehess_to_rhess(X, eg, problem.ehess(X, V), V)
    pre = lambda V: manifold.tangent_project(X, problem.precond(X, V))
    res = solver.truncated_cg(X, g, hvp, pre, jnp.asarray(10.0), max_iters=50)
    m = float(manifold.inner(g, res.eta) + 0.5 * manifold.inner(res.eta, res.heta))
    assert m < 0
    assert float(manifold.norm(res.eta)) <= 10.0 * (1 + 1e-9)


def test_rtr_solves_noiseless_graph_exactly(rng):
    meas, edges, problem, (Rs, ts) = setup_problem(rng, num_lc=8)
    n = meas.num_poses
    ylift = jnp.eye(3, dtype=jnp.float64)
    # Perturbed start: odometry-ish with noise.
    X0 = lift(jnp.asarray(
        np.concatenate([Rs + 0.1 * rng.standard_normal(Rs.shape),
                        (ts + 0.5 * rng.standard_normal(ts.shape))[..., None]], -1)),
        ylift)
    X0 = manifold.project(X0)
    params = SolverParams(initial_radius=10.0, max_inner_iters=50)
    out = solver.rtr_solve(problem, X0, params, max_iters=100, grad_norm_tol=1e-8)
    # Noiseless: optimal cost 0, exact recovery after rounding.
    assert float(out.f) < 1e-12
    T = round_solution(out.X, ylift)
    assert trajectory_error(T, Rs, ts) < 1e-5


def test_rtr_monotone_and_reaches_tol(rng):
    meas, edges, problem, _ = setup_problem(rng, n=25, num_lc=12,
                                            rot_noise=0.05, trans_noise=0.05)
    n = meas.num_poses
    from dpgo_tpu.ops import chordal
    ylift = fixed_stiefel(5, 3, jnp.float64)
    X0 = lift(chordal.chordal_initialization(edges, n), ylift)
    f0 = float(problem.cost(X0))
    params = SolverParams(initial_radius=100.0, max_inner_iters=50)
    out = solver.rtr_solve(problem, X0, params, max_iters=200, grad_norm_tol=1e-6)
    assert float(out.f) <= f0
    assert float(out.grad_norm) < 1e-6


def test_rtr_single_step_decreases_cost(rng):
    meas, edges, problem, _ = setup_problem(rng, n=20, num_lc=10,
                                            rot_noise=0.05, trans_noise=0.05)
    n = meas.num_poses
    from dpgo_tpu.ops import chordal
    ylift = fixed_stiefel(5, 3, jnp.float64)
    X0 = lift(chordal.chordal_initialization(edges, n), ylift)
    # RBCD per-iteration budget (PGOAgent.cpp:1131-1137).
    params = SolverParams(grad_norm_tol=1e-2, max_inner_iters=10,
                          initial_radius=100.0)
    out = solver.rtr_single_step(problem, X0, params)
    f0 = float(problem.cost(X0))
    assert float(out.f) <= f0
    # Either the step was accepted or the gradient was already below tol.
    assert bool(out.done) or float(out.grad_norm) < 1e-2


def test_rtr_single_step_noop_below_tol(rng):
    meas, edges, problem, (Rs, ts) = setup_problem(rng, num_lc=6)
    ylift = jnp.eye(3, dtype=jnp.float64)
    X_opt = lift(jnp.asarray(np.concatenate([Rs, ts[..., None]], -1)), ylift)
    params = SolverParams(grad_norm_tol=1e-2)
    out = solver.rtr_single_step(problem, X_opt, params)
    # Already optimal (noiseless truth): unchanged.
    assert np.allclose(out.X, X_opt, atol=1e-12)


def test_rgd_step_decreases_cost(rng):
    meas, edges, problem, _ = setup_problem(rng, n=15, num_lc=6,
                                            rot_noise=0.05, trans_noise=0.05)
    from dpgo_tpu.ops import chordal
    ylift = fixed_stiefel(5, 3, jnp.float64)
    X0 = lift(chordal.chordal_initialization(edges, meas.num_poses), ylift)
    X1 = solver.rgd_step(problem, X0, stepsize=1e-4)
    assert float(problem.cost(X1)) < float(problem.cost(X0))


def test_rgd_linesearch_converges(rng):
    meas, edges, problem, _ = setup_problem(rng, n=10, num_lc=4,
                                            rot_noise=0.02, trans_noise=0.02)
    from dpgo_tpu.ops import chordal
    ylift = fixed_stiefel(5, 3, jnp.float64)
    X0 = lift(chordal.chordal_initialization(edges, meas.num_poses), ylift)
    X1 = solver.rgd_linesearch(problem, X0, max_iters=50, grad_norm_tol=1e-4)
    assert float(problem.cost(X1)) <= float(problem.cost(X0))


def test_block_jacobi_precond_speeds_tcg(rng):
    # The preconditioner must reduce tCG iterations to a fixed residual
    # target vs identity (SURVEY hard-part #2: validate iteration counts).
    meas, edges, problem, _ = setup_problem(rng, n=40, num_lc=20,
                                            rot_noise=0.05, trans_noise=0.05)
    n = meas.num_poses
    from dpgo_tpu.ops import chordal
    ylift = fixed_stiefel(5, 3, jnp.float64)
    X = lift(chordal.chordal_initialization(edges, n), ylift)
    eg = problem.egrad(X)
    g = manifold.rgrad(X, eg)
    hvp = lambda V: manifold.ehess_to_rhess(X, eg, problem.ehess(X, V), V)

    pre = lambda V: manifold.tangent_project(X, problem.precond(X, V))
    res_pre = solver.truncated_cg(X, g, hvp, pre, jnp.asarray(1e9), 500, kappa=1e-6)
    res_id = solver.truncated_cg(X, g, hvp, lambda V: V, jnp.asarray(1e9), 500, kappa=1e-6)
    assert int(res_pre.iters) <= int(res_id.iters)
