"""Mesh-sharded RBCD: the collective code paths, run on the virtual 8-device
CPU mesh (SURVEY.md section 4 item (e) — multi-device tests the reference
never had)."""

import jax.numpy as jnp
import numpy as np
import pytest

from dpgo_tpu.config import AgentParams, Schedule
from dpgo_tpu.models import rbcd
from dpgo_tpu.parallel import make_mesh, make_multislice_mesh, \
    make_sharded_step, shard_problem, solve_rbcd_sharded
from dpgo_tpu.utils.g2o import read_g2o
from dpgo_tpu.utils.partition import partition_contiguous

from synthetic import make_measurements


def _setup(meas, num_robots, params, dtype=jnp.float64):
    part = partition_contiguous(meas, num_robots)
    graph, meta = rbcd.build_graph(part, params.r, dtype)
    X0 = rbcd.centralized_chordal_init(part, meta, graph, dtype)
    state = rbcd.init_state(graph, meta, X0, params=params)
    return part, graph, meta, state


@pytest.mark.parametrize("n_dev,schedule", [
    (8, Schedule.JACOBI),
    (8, Schedule.GREEDY),
    (4, Schedule.JACOBI),   # 2 agents per device
    (8, Schedule.ASYNC),
    (8, Schedule.COLORED),
    (4, Schedule.COLORED),
])
def test_sharded_matches_single_device(rng, n_dev, schedule):
    """The sharded round body is the same math as the single-device one, so
    three rounds must agree to float64 reduction-order tolerance."""
    meas, _ = make_measurements(rng, n=48, d=3, num_lc=14, rot_noise=0.01,
                                trans_noise=0.01)
    params = AgentParams(d=3, r=5, num_robots=8, schedule=schedule)
    _, graph, meta, state = _setup(meas, 8, params)

    mesh = make_mesh(n_dev)
    sh_state, sh_graph = shard_problem(mesh, state, graph)
    step = make_sharded_step(mesh, meta, params)

    for _ in range(3):
        state = rbcd.rbcd_step(state, graph, meta, params)
        sh_state = step(sh_state, sh_graph)

    np.testing.assert_allclose(np.asarray(sh_state.X), np.asarray(state.X),
                               atol=1e-9)
    np.testing.assert_allclose(np.asarray(sh_state.rel_change),
                               np.asarray(state.rel_change), atol=1e-9)
    assert np.array_equal(np.asarray(sh_state.ready), np.asarray(state.ready))


@pytest.mark.parametrize("num_slices", [2, 4])
def test_multislice_mesh_matches_single_device(rng, num_slices):
    """BASELINE config #5's multi-slice deployment: agents shard over the
    flattened ("dcn", "ici") product axis of a 2-D mesh — the identical
    round body, with the pose-exchange all_gather spanning both axes (XLA
    routes each hop over the interconnect that links the devices).  The
    virtual 8-device CPU mesh validates the 2-axis program end to end."""
    meas, _ = make_measurements(rng, n=48, d=3, num_lc=14, rot_noise=0.01,
                                trans_noise=0.01)
    params = AgentParams(d=3, r=5, num_robots=8, schedule=Schedule.JACOBI)
    _, graph, meta, state = _setup(meas, 8, params)

    mesh = make_multislice_mesh(num_slices)
    assert mesh.axis_names == ("dcn", "ici")
    sh_state, sh_graph = shard_problem(mesh, state, graph)
    step = make_sharded_step(mesh, meta, params)

    for _ in range(3):
        state = rbcd.rbcd_step(state, graph, meta, params)
        sh_state = step(sh_state, sh_graph)

    np.testing.assert_allclose(np.asarray(sh_state.X), np.asarray(state.X),
                               atol=1e-9)
    assert np.array_equal(np.asarray(sh_state.ready), np.asarray(state.ready))


def test_multislice_solve_end_to_end(rng):
    """Full solve over the 2x4 multi-slice mesh (solve_rbcd_sharded with an
    explicit multislice mesh): converges like the 1-D mesh path; the
    ppermute exchange is correctly rejected on a 2-D mesh."""
    meas, _ = make_measurements(rng, n=40, d=3, num_lc=12, rot_noise=0.01,
                                trans_noise=0.01)
    params = AgentParams(d=3, r=5, num_robots=8, rel_change_tol=0.0)
    mesh = make_multislice_mesh(2)
    res = solve_rbcd_sharded(meas, num_robots=8, mesh=mesh, params=params,
                             max_iters=100, grad_norm_tol=0.1)
    assert res.terminated_by == "grad_norm"
    costs = np.asarray(res.cost_history)
    assert np.all(np.diff(costs) <= 1e-9)

    with pytest.raises(ValueError, match="1-D mesh"):
        solve_rbcd_sharded(meas, num_robots=8, mesh=mesh, params=params,
                           max_iters=4, exchange="ppermute")


def test_sharded_solve_smallgrid(data_dir):
    """End-to-end sharded solve on the reference's canonical demo dataset
    (smallGrid3D, README.md:31-34) with 8 agents on 8 devices: the
    centralized gradient-norm gate of MultiRobotExample.cpp:238 must be met
    and cost must decrease monotonically."""
    meas = read_g2o(f"{data_dir}/smallGrid3D.g2o")
    params = AgentParams(d=3, r=5, num_robots=8, rel_change_tol=1e-4)
    res = solve_rbcd_sharded(meas, num_robots=8, mesh=make_mesh(8),
                             params=params, max_iters=100, grad_norm_tol=0.1)
    assert res.terminated_by == "grad_norm"
    costs = np.asarray(res.cost_history)
    assert np.all(np.diff(costs) <= 1e-9)
    assert res.T.shape == (meas.num_poses, 3, 4)


def test_sharded_matches_single_device_accel_robust(rng):
    """M4 paths (Nesterov aux exchange + GNC weight rounds + restart rounds)
    must also agree between the sharded and single-device round bodies."""
    from dpgo_tpu.config import RobustCostParams, RobustCostType

    meas, _ = make_measurements(rng, n=48, d=3, num_lc=14, rot_noise=0.01,
                                trans_noise=0.01, outlier_lc=4)
    params = AgentParams(
        d=3, r=5, num_robots=8, schedule=Schedule.JACOBI,
        acceleration=True, restart_interval=4,
        robust=RobustCostParams(cost_type=RobustCostType.GNC_TLS,
                                gnc_barc=0.5),
        robust_opt_inner_iters=3)
    _, graph, meta, state = _setup(meas, 8, params)

    mesh = make_mesh(8)
    sh_state, sh_graph = shard_problem(mesh, state, graph)
    step = make_sharded_step(mesh, meta, params)

    for it in range(8):
        uw = (it + 1) % 3 == 0
        rs = (it + 1) % 4 == 0
        state = rbcd.rbcd_step(state, graph, meta, params,
                               update_weights=uw, restart=rs)
        sh_state = step(sh_state, sh_graph, update_weights=uw, restart=rs)

    np.testing.assert_allclose(np.asarray(sh_state.X), np.asarray(state.X),
                               atol=1e-9)
    np.testing.assert_allclose(np.asarray(sh_state.weights),
                               np.asarray(state.weights), atol=1e-9)
    np.testing.assert_allclose(np.asarray(sh_state.V), np.asarray(state.V),
                               atol=1e-9)
    assert np.isclose(float(sh_state.mu), float(state.mu))


def test_sharded_solve_robust_accel(rng):
    """End-to-end sharded robust+accelerated solve rejects outliers."""
    from dpgo_tpu.config import RobustCostParams, RobustCostType, SolverParams

    meas, _ = make_measurements(rng, n=32, d=3, num_lc=10, outlier_lc=4)
    params = AgentParams(
        d=3, r=5, num_robots=8, schedule=Schedule.JACOBI,
        acceleration=True, restart_interval=30,
        robust=RobustCostParams(cost_type=RobustCostType.GNC_TLS,
                                gnc_barc=0.5),
        robust_opt_inner_iters=10, rel_change_tol=1e-8,
        solver=SolverParams(grad_norm_tol=1e-6))
    res = solve_rbcd_sharded(meas, num_robots=8, mesh=make_mesh(8),
                             params=params, max_iters=300, grad_norm_tol=1e-5)
    w = np.asarray(res.weights)
    assert np.all(w[-4:] < 0.01)
    assert np.all(w[:-4] > 0.99)


def test_sharded_fused_rounds_match_per_round(rng):
    """The fused mesh loop (fori_loop inside shard_map, one dispatch) must
    reproduce per-round sharded stepping exactly."""
    from dpgo_tpu.parallel import make_sharded_multi_step

    meas, _ = make_measurements(rng, n=48, d=3, num_lc=14, rot_noise=0.01,
                                trans_noise=0.01)
    params = AgentParams(d=3, r=5, num_robots=8, schedule=Schedule.JACOBI)
    _, graph, meta, state = _setup(meas, 8, params)

    mesh = make_mesh(8)
    sh_state, sh_graph = shard_problem(mesh, state, graph)
    step = make_sharded_step(mesh, meta, params)
    multi = make_sharded_multi_step(mesh, meta, params)

    seq = sh_state
    for _ in range(4):
        seq = step(seq, sh_graph)
    fused = multi(sh_state, sh_graph, 4)

    assert int(fused.iteration) == 4
    np.testing.assert_allclose(np.asarray(fused.X), np.asarray(seq.X),
                               atol=1e-12)
    np.testing.assert_allclose(np.asarray(fused.rel_change),
                               np.asarray(seq.rel_change), atol=1e-12)


def test_ppermute_exchange_matches_all_gather(rng):
    """The shift-based ppermute pose exchange must be bitwise-identical to
    the all_gather v1 — same rounds, same state — including with several
    agents per device and with the accel/robust special rounds."""
    from dpgo_tpu.config import RobustCostParams, RobustCostType
    from dpgo_tpu.parallel.sharded import _exchange_plan

    meas, _ = make_measurements(rng, n=64, d=3, num_lc=20, rot_noise=0.01,
                                trans_noise=0.01, outlier_lc=4)
    params = AgentParams(
        d=3, r=5, num_robots=16, schedule=Schedule.JACOBI,
        acceleration=True, restart_interval=4,
        robust=RobustCostParams(cost_type=RobustCostType.GNC_TLS,
                                gnc_barc=0.5),
        robust_opt_inner_iters=3)
    _, graph, meta, state = _setup(meas, 16, params)

    mesh = make_mesh(8)  # 2 agents per device
    sh_state, sh_graph = shard_problem(mesh, state, graph)
    shifts, plan = _exchange_plan(mesh, meta, sh_graph, "ppermute")
    assert len(shifts) >= 1
    step_ag = make_sharded_step(mesh, meta, params)
    step_pp = make_sharded_step(mesh, meta, params, shifts, plan)

    sa, sp = sh_state, sh_state
    for it in range(8):
        uw = (it + 1) % 3 == 0
        rs = (it + 1) % 4 == 0
        sa = step_ag(sa, sh_graph, update_weights=uw, restart=rs)
        sp = step_pp(sp, sh_graph, update_weights=uw, restart=rs)
    np.testing.assert_array_equal(np.asarray(sp.X), np.asarray(sa.X))
    np.testing.assert_array_equal(np.asarray(sp.weights),
                                  np.asarray(sa.weights))
    np.testing.assert_array_equal(np.asarray(sp.V), np.asarray(sa.V))


def test_ppermute_solve_end_to_end(data_dir):
    """solve_rbcd_sharded(exchange='ppermute') reaches the demo gate on
    smallGrid3D with the same trace as the all_gather solve."""
    meas = read_g2o(f"{data_dir}/smallGrid3D.g2o")
    params = AgentParams(d=3, r=5, num_robots=8, rel_change_tol=1e-4)
    res_a = solve_rbcd_sharded(meas, num_robots=8, mesh=make_mesh(8),
                               params=params, max_iters=60,
                               grad_norm_tol=0.1)
    res_p = solve_rbcd_sharded(meas, num_robots=8, mesh=make_mesh(8),
                               params=params, max_iters=60,
                               grad_norm_tol=0.1, exchange="ppermute")
    assert res_p.terminated_by == res_a.terminated_by
    assert res_p.iterations == res_a.iterations
    np.testing.assert_array_equal(np.asarray(res_p.T), np.asarray(res_a.T))


def test_comm_bytes_model(rng):
    """The ppermute route must model strictly less traffic than all_gather
    on a chain-adjacency partition, and acceleration doubles the exchange."""
    from dpgo_tpu.models.rbcd import plan_ppermute
    from dpgo_tpu.parallel import comm_bytes_per_round

    meas, _ = make_measurements(rng, n=64, d=3, num_lc=0)  # pure chain
    params = AgentParams(d=3, r=5, num_robots=8)
    part = partition_contiguous(meas, 8)
    graph, meta = rbcd.build_graph(part, 5, jnp.float64)
    shifts, _plan = plan_ppermute(graph, 8, 8)
    # Odometry chain: only +-1 device adjacency.
    assert set(shifts) <= {1, 7}
    ag = comm_bytes_per_round(meta, 8)
    pp = comm_bytes_per_round(meta, 8, shifts=shifts)
    assert pp < ag
    # Acceleration doubles the table exchange (aux poses), not the greedy
    # gradient-norm gather (modeled only when the schedule is greedy).
    greedy = (8 - 1) * (meta.num_robots // 8) * 4
    assert comm_bytes_per_round(meta, 8, accel=True) == 2 * ag
    assert comm_bytes_per_round(meta, 8, accel=True, greedy=True) \
        == 2 * ag + greedy


def _compiled_collective_bytes(txt: str, n_dev: int):
    """Per-device cross-device bytes of a compiled program's collectives,
    parsed from partitioned HLO: an all-gather sends all but the device's
    own shard of its output on the ring; a collective-permute forwards its
    operand block once."""
    import re

    total, ops = 0, []
    for line in txt.splitlines():
        m = re.search(r"= (f64|f32|s32|u32|pred)\[([\d,]*)\][^ ]* "
                      r"(all-gather|collective-permute)\(", line)
        if not m:
            continue
        ty, dims, op = m.groups()
        size = 1
        for x in dims.split(","):
            if x:
                size *= int(x)
        nbytes = size * {"f64": 8, "f32": 4, "s32": 4, "u32": 4,
                         "pred": 1}[ty]
        sent = nbytes * (n_dev - 1) // n_dev if op == "all-gather" else nbytes
        ops.append(op)
        total += sent
    return total, ops


def test_comm_model_matches_compiled_collectives(rng):
    """``comm_bytes_per_round`` must equal the bytes moved by the
    collectives XLA actually emits for the sharded round, for both exchange
    backends and for the greedy schedule's extra gradient-norm gather
    (VERDICT round-1 item 10: the model validated against measured
    collectives, not hand-counting)."""
    from dpgo_tpu.parallel import comm_bytes_per_round
    from dpgo_tpu.parallel.sharded import (_exchange_plan, make_mesh,
                                           make_sharded_step, shard_problem)

    meas, _ = make_measurements(rng, n=64, d=3, num_lc=0)  # chain adjacency
    mesh = make_mesh(8)
    part = partition_contiguous(meas, 8)
    graph, meta = rbcd.build_graph(part, 5, jnp.float64)
    X0 = rbcd.centralized_chordal_init(part, meta, graph, jnp.float64)

    for schedule, greedy in ((Schedule.JACOBI, False),
                             (Schedule.GREEDY, True)):
        params = AgentParams(d=3, r=5, num_robots=8, schedule=schedule)
        state = rbcd.init_state(graph, meta, X0, params=params)
        state_s, graph_s = shard_problem(mesh, state, graph)
        for exchange in ("all_gather", "ppermute"):
            shifts, plan = _exchange_plan(mesh, meta, graph_s, exchange)
            step = make_sharded_step(mesh, meta, params, shifts, plan)
            txt = step.lower(state_s, graph_s, update_weights=False,
                             restart=False).compile().as_text()
            got, ops = _compiled_collective_bytes(txt, 8)
            model = comm_bytes_per_round(
                meta, 8, None if exchange == "all_gather" else shifts,
                itemsize=8, greedy=greedy)
            assert got == model, (schedule, exchange, got, model, ops)
        # Chain adjacency: the ppermute route uses only the +-1 shifts, so
        # its modeled (= compiled) traffic is a fraction of all_gather's.
        assert set(shifts) <= {1, 7}
        assert comm_bytes_per_round(meta, 8, shifts, itemsize=8,
                                    greedy=greedy) \
            < comm_bytes_per_round(meta, 8, None, itemsize=8, greedy=greedy)


def test_ppermute_plan_routing(rng):
    """plan_ppermute routes every masked neighbor slot to the correct
    (shift, local robot) pair and only emits shifts that carry edges."""
    from dpgo_tpu.models.rbcd import plan_ppermute
    from dpgo_tpu.utils.partition import partition_contiguous as pc

    meas, _ = make_measurements(rng, n=48, d=3, num_lc=14)
    part = pc(meas, 8)
    graph, meta = rbcd.build_graph(part, 5, jnp.float64)
    n_dev = 4  # 2 agents per device
    shifts, plan = plan_ppermute(graph, 8, n_dev)
    A_loc = 8 // n_dev
    nbr_robot = np.asarray(graph.nbr_robot)
    nbr_mask = np.asarray(graph.nbr_mask) > 0
    src = np.asarray(plan.src)
    lrobot = np.asarray(plan.lrobot)
    for a in range(8):
        for m in range(nbr_robot.shape[1]):
            if not nbr_mask[a, m]:
                continue
            b = nbr_robot[a, m]
            s = (a // A_loc - b // A_loc) % n_dev
            expect = 0 if s == 0 else 1 + shifts.index(s)
            assert src[a, m] == expect, (a, m)
            assert lrobot[a, m] == b % A_loc
    for s in shifts:
        assert s != 0


def test_mesh_size_divisibility(rng):
    meas, _ = make_measurements(rng, n=24, d=3, num_lc=5)
    params = AgentParams(d=3, r=5, num_robots=6)
    _, graph, meta, state = _setup(meas, 6, params)
    with pytest.raises(ValueError, match="multiple of mesh size"):
        shard_problem(make_mesh(4), state, graph)


def test_sharded_64_agents_on_8_devices(rng):
    """BASELINE config #5 scale: 64 agents over an 8-device mesh (8 agent
    blocks per shard — the multi-slice layout, DCN being the same code
    path as ICI in XLA collectives).  Three rounds must agree with the
    single-device solver."""
    meas, _ = make_measurements(rng, n=256, d=3, num_lc=80,
                                rot_noise=0.01, trans_noise=0.01)
    params = AgentParams(d=3, r=5, num_robots=64, schedule=Schedule.JACOBI)
    _, graph, meta, state = _setup(meas, 64, params)

    mesh = make_mesh(8)
    sh_state, sh_graph = shard_problem(mesh, state, graph)
    step = make_sharded_step(mesh, meta, params)
    for _ in range(3):
        state = rbcd.rbcd_step(state, graph, meta, params)
        sh_state = step(sh_state, sh_graph)
    np.testing.assert_allclose(np.asarray(sh_state.X), np.asarray(state.X),
                               atol=1e-9)
