"""The cross-round perf ledger (ISSUE 16): record discovery and
normalization across the three bench families, the rendered/JSON forms,
the ``check_bench_floor`` schema gate, and the regress trend gate that
catches cross-round slides the pairwise compare never sees."""

import json
import subprocess
import sys

import pytest

from dpgo_tpu.obs import regress
from dpgo_tpu.obs.ledger import PerfLedger, discover_records, load_ledger


def _write(d, name, obj):
    p = d / name
    p.write_text(json.dumps(obj))
    return str(p)


def _bench(value, vs_baseline, rc=0, parity=None):
    parsed = {"metric": "rbcd_rounds_per_sec", "value": value,
              "unit": "rounds/s", "vs_baseline": vs_baseline,
              "cpu_arm_band": {"min": 20.0, "max": 30.0}}
    if parity is not None:
        parsed["kernel_parity_max_abs_diff"] = parity
    return {"n": 1, "cmd": "python bench.py", "rc": rc, "tail": "",
            "parsed": parsed}


def _multichip(value, overlap_eff=None, syncs=None):
    rec = {"record": "MULTICHIP", "ok": True, "n_devices": 8,
           "metric": "sharded_rounds_per_sec", "value": value,
           "unit": "rounds/s", "verdict_every": 8}
    if overlap_eff is not None:
        rec["overlap"] = {"efficiency": overlap_eff}
    if syncs is not None:
        rec["host_syncs_per_100_rounds"] = syncs
    return rec


def _fixture_root(tmp_path):
    d = tmp_path / "records"
    d.mkdir()
    _write(d, "BENCH_r01.json", _bench(100.0, 3.0))
    _write(d, "BENCH_r02.json", _bench(110.0, 3.2, parity=3e-5))
    _write(d, "BENCH_r03.json", _bench(120.0, 3.5, parity=2e-5))
    # Placeholder round (pre-metric era) and a genuine failed run.
    _write(d, "MULTICHIP_r01.json",
           {"n_devices": 0, "ok": False, "rc": 1, "skipped": False,
            "tail": "no devices"})
    _write(d, "MULTICHIP_r02.json", _multichip(40.0, overlap_eff=-0.05,
                                               syncs=25.0))
    _write(d, "MULTICHIP_r03.json", _multichip(44.0, overlap_eff=-0.03,
                                               syncs=25.0))
    _write(d, "FLEET_r01.json",
           {"ok": True, "qps": [{"replicas": 1, "qps": 5.0},
                                {"replicas": 2, "qps": 9.0}],
            "scaling_1_to_2": 1.8,
            "cold_start": {"compile_seconds_total": 30.0}})
    _write(d, "NOT_A_RECORD.json", {"x": 1})
    (d / "BENCH_notes.txt").write_text("ignored")
    return d


def test_discover_records_families_and_order(tmp_path):
    d = _fixture_root(tmp_path)
    found = discover_records(str(d))
    assert [(f, r) for f, r, _ in found] == [
        ("BENCH", 1), ("BENCH", 2), ("BENCH", 3),
        ("FLEET", 1),
        ("MULTICHIP", 1), ("MULTICHIP", 2), ("MULTICHIP", 3)]


def test_load_ledger_normalizes_all_families(tmp_path):
    d = _fixture_root(tmp_path)
    led = load_ledger(str(d))
    assert led.families() == ["BENCH", "FLEET", "MULTICHIP"]
    assert len(led.rows) == 7
    b = led.family_rows("BENCH")
    assert all(r["ok"] for r in b)
    assert [r["value"] for r in b] == [100.0, 110.0, 120.0]
    assert b[1]["extras"]["kernel_parity_max_abs_diff"] == 3e-5
    assert b[0]["extras"]["band_min"] == 20.0
    m = led.family_rows("MULTICHIP")
    # r01 is an honest placeholder: present, failed, metric-less.
    assert m[0]["ok"] is False and m[0]["value"] is None
    assert m[1]["extras"]["overlap_efficiency"] == -0.05
    f = led.family_rows("FLEET")
    assert f[0]["value"] == 9.0          # widest replica arm's QPS
    assert f[0]["extras"]["replicas"] == 2
    assert f[0]["extras"]["scaling_1_to_2"] == 1.8
    # Series skip placeholders.
    assert led.series("MULTICHIP") == [(2, 40.0), (3, 44.0)]
    assert led.series("BENCH", "vs_baseline") == \
        [(1, 3.0), (2, 3.2), (3, 3.5)]


def test_load_ledger_corrupt_file_becomes_failed_row(tmp_path):
    d = tmp_path / "r"
    d.mkdir()
    (d / "BENCH_r01.json").write_text("{not json")
    led = load_ledger(str(d))
    assert len(led.rows) == 1
    assert led.rows[0]["ok"] is False
    assert "error" in led.rows[0]["extras"]


def test_render_and_json_forms(tmp_path):
    d = _fixture_root(tmp_path)
    led = load_ledger(str(d))
    txt = led.render()
    assert "perf ledger: 7 rounds across 3 families" in txt
    assert "[BENCH] (3 rounds)" in txt and "[MULTICHIP] (3 rounds)" in txt
    assert "FAIL" in txt                      # MULTICHIP r01 shown honestly
    assert "trend value:" in txt and "vs_baseline" in txt
    obj = led.to_json()
    assert obj["record"] == "LEDGER" and obj["rounds"] == 7
    assert obj["families"] == ["BENCH", "FLEET", "MULTICHIP"]
    json.dumps(obj)                           # fully serializable


def test_check_bench_floor_validates_ledger_schema(tmp_path):
    from tools import check_bench_floor

    d = _fixture_root(tmp_path)
    obj = load_ledger(str(d)).to_json()
    check_bench_floor.check_ledger(obj)       # clean: no raise
    # Schema violations the gate must catch.
    bad = json.loads(json.dumps(obj))
    bad["rows"][0].pop("extras")
    with pytest.raises(SystemExit):
        check_bench_floor.check_ledger(bad)
    bad = json.loads(json.dumps(obj))
    bad["rows"][0]["family"] = "WAT"
    with pytest.raises(SystemExit):
        check_bench_floor.check_ledger(bad)
    bad = json.loads(json.dumps(obj))
    bad["rounds"] = 99
    with pytest.raises(SystemExit):
        check_bench_floor.check_ledger(bad)


def test_trend_gate_passes_monotone_history(tmp_path):
    d = _fixture_root(tmp_path)
    gate = regress.trend_gate(load_ledger(str(d)))
    assert gate["rc"] == 0 and gate["regressions"] == []
    # Every declared series with >= 2 readings got gated.
    assert "BENCH:value" in gate["trends"]
    assert "MULTICHIP:overlap_efficiency" in gate["trends"]
    txt = regress.render_trend(gate)
    assert "no trend regression" in txt


def test_trend_gate_catches_slide_and_failed_latest_round(tmp_path):
    d = _fixture_root(tmp_path)
    # A slide: the new round is >10% below the prior band min.
    _write(d, "BENCH_r04.json", _bench(80.0, 2.0))
    gate = regress.trend_gate(load_ledger(str(d)))
    assert gate["rc"] == 2
    assert "BENCH:value" in gate["regressions"]
    assert "BENCH:vs_baseline" in gate["regressions"]
    assert "below prior band min" in \
        gate["trends"]["BENCH:value"]["reason"]
    # A latest round that failed outright regresses regardless of values.
    _write(d, "MULTICHIP_r04.json",
           {"n_devices": 8, "ok": False, "rc": 1, "skipped": False,
            "tail": "crash"})
    gate = regress.trend_gate(load_ledger(str(d)))
    assert "MULTICHIP:ok" in gate["regressions"]
    assert "ok=false" in gate["trends"]["MULTICHIP:ok"]["reason"]
    txt = regress.render_trend(gate)
    assert "TREND REGRESSION" in txt


def test_trend_gate_direction_lower_is_better(tmp_path):
    d = tmp_path / "r"
    d.mkdir()
    _write(d, "BENCH_r01.json", _bench(100.0, 3.0, parity=1e-5))
    _write(d, "BENCH_r02.json", _bench(101.0, 3.0, parity=1e-5))
    _write(d, "BENCH_r03.json", _bench(102.0, 3.0, parity=9e-5))
    gate = regress.trend_gate(load_ledger(str(d)))
    assert "BENCH:kernel_parity_max_abs_diff" in gate["regressions"]
    assert "above prior band max" in \
        gate["trends"]["BENCH:kernel_parity_max_abs_diff"]["reason"]


def test_checked_in_records_cover_every_round_and_gate_clean():
    """ISSUE 16 acceptance: the REAL repo records all load — every
    BENCH_r*/MULTICHIP_r* file becomes a row — the machine form passes
    the schema gate, and today's history carries no trend regression."""
    from tools import check_bench_floor

    led = load_ledger("/root/repo")
    names = {(r["family"], r["round"]) for r in led.rows}
    import glob as _glob
    import re as _re
    on_disk = set()
    for p in _glob.glob("/root/repo/*.json"):
        m = _re.match(r"^(BENCH|MULTICHIP|FLEET)_r(\d+)\.json$",
                      p.rsplit("/", 1)[1])
        if m:
            on_disk.add((m.group(1), int(m.group(2))))
    assert on_disk and names == on_disk
    check_bench_floor.check_ledger(led.to_json())
    assert regress.trend_gate(led)["rc"] == 0


def test_checked_in_fleet_record_pins_out_of_process_scaling():
    """ISSUE 17 acceptance pin: the checked-in ``FLEET_r01.json`` came
    from the OUT-OF-PROCESS bench (``bench_fleet.py --procs``) — real OS
    processes behind the packed-v2 TCP front-end, a real SIGKILL in the
    soak — and it holds the same floors as the in-process fleet: >= 1.7x
    1->2 scaling, zero lost sessions, a respawned process, and a 0s-XLA
    warm restart."""
    from tools import check_bench_floor

    with open("/root/repo/FLEET_r01.json") as fh:
        rec = json.load(fh)
    assert rec["out_of_process"] is True
    assert rec["scaling_1_to_2"] >= 1.7
    assert rec["soak"]["lost"] == 0
    assert rec["soak"]["migrations"] >= 1
    assert rec["soak"]["respawns"] >= 1
    assert rec["cold_start"]["compile_seconds_total"] == 0
    check_bench_floor.check_fleet(rec)  # exits 1 on any floor violation
    row = load_ledger("/root/repo").family_rows("FLEET")[0]
    assert row["ok"] and row["round"] == 1
    assert row["value"] == pytest.approx(rec["qps"][-1]["qps"])
    assert row["extras"]["scaling_1_to_2"] == rec["scaling_1_to_2"]


def test_report_ledger_cli_roundtrip(tmp_path):
    """``report --ledger ROOT`` renders the table (and ``--json`` emits
    the machine form check_bench_floor validates); ``regress --ledger``
    returns the gate's exit code."""
    d = _fixture_root(tmp_path)
    env_cmd = [sys.executable, "-m", "dpgo_tpu.obs.report",
               "--ledger", str(d)]
    out = subprocess.run(env_cmd, capture_output=True, text=True,
                         timeout=120)
    assert out.returncode == 0, out.stderr
    assert "perf ledger" in out.stdout
    out = subprocess.run(env_cmd + ["--json"], capture_output=True,
                         text=True, timeout=120)
    assert out.returncode == 0, out.stderr
    obj = json.loads(out.stdout)
    assert obj["record"] == "LEDGER"
    # The regress CLI gates the same root.
    assert regress.run_trend(str(d)) == 0
    _write(d, "BENCH_r04.json", _bench(10.0, 0.5))
    assert regress.run_trend(str(d)) == 2
