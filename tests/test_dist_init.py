"""M5 tests: distributed multi-robot initialization + async deployment path.

Covers the reference's inter-agent frame alignment
(``PGOAgent::initializeInGlobalFrame`` and helpers,
``src/PGOAgent.cpp:250-432``): per-agent local init, robust GNC alignment
against an initialized neighbor, BFS propagation from the anchor robot, and
the full no-centralized-init solve — including with outlier inter-robot
loop closures, the case the robust two-stage averaging exists for.
"""

import dataclasses

import jax.numpy as jnp
import numpy as np
import pytest

from dpgo_tpu.config import (AgentParams, RobustCostParams, RobustCostType,
                             Schedule, SolverParams)
from dpgo_tpu.models import dist_init, rbcd
from dpgo_tpu.utils.partition import partition_contiguous
from synthetic import make_measurements, random_rotation, trajectory_error


def test_local_initialization_per_agent_frames(rng):
    meas, _ = make_measurements(rng, n=24, d=3, num_lc=8)
    part = partition_contiguous(meas, 4)
    params = AgentParams(d=3, r=5, num_robots=4)
    T = dist_init.local_initialization(part, params)
    assert T.shape == (4, part.n_max, 3, 4)
    # Pose 0 of each agent is (approximately) that agent's frame origin:
    # chordal init pins pose 0 at identity.
    for a in range(4):
        assert np.allclose(T[a, 0, :, :3], np.eye(3), atol=1e-6)
        assert np.allclose(T[a, 0, :, 3], 0.0, atol=1e-6)


def test_distributed_init_aligns_frames(rng):
    # Noiseless graph: the aligned initialization must reproduce the global
    # ground truth exactly (up to gauge), because every candidate transform
    # is exact.
    meas, (Rs, ts) = make_measurements(rng, n=24, d=3, num_lc=10)
    part = partition_contiguous(meas, 4)
    params = AgentParams(d=3, r=5, num_robots=4)
    graph, meta = rbcd.build_graph(part, params.r, jnp.float64)
    X0 = dist_init.distributed_initialization(part, meta, graph, params)
    assert np.isfinite(np.asarray(X0)).all()
    Xg = rbcd.gather_to_global(X0, graph, meas.num_poses)
    T = rbcd.round_global(Xg, rbcd.lifting_matrix(meta, jnp.float64))
    assert trajectory_error(T, Rs, ts) < 1e-6


def test_distributed_init_robust_to_outlier_shared_edges(rng):
    # Corrupt a subset of the INTER-robot loop closures: the GNC rotation
    # averaging must reject them and still align every frame correctly.
    meas, (Rs, ts) = make_measurements(rng, n=32, d=3, num_lc=24)
    part = partition_contiguous(meas, 4)
    r1, r2 = np.asarray(part.meas.r1), np.asarray(part.meas.r2)
    shared = np.nonzero(r1 != r2)[0]
    assert len(shared) >= 6, "test graph needs enough inter-robot edges"
    # Corrupt ~1/3 of the shared edges (keep a robust majority per pair).
    bad = shared[:: 3]
    R_new = np.array(part.meas.R)
    t_new = np.array(part.meas.t)
    for k in bad:
        R_new[k] = random_rotation(rng, 3)
        t_new[k] = 10.0 * rng.standard_normal(3)
    meas_bad = dataclasses.replace(part.meas, R=R_new, t=t_new)
    part_bad = dataclasses.replace(part, meas=meas_bad)

    params = AgentParams(d=3, r=5, num_robots=4)
    graph, meta = rbcd.build_graph(part_bad, params.r, jnp.float64)
    X0 = dist_init.distributed_initialization(part_bad, meta, graph, params)
    Xg = rbcd.gather_to_global(X0, graph, meas.num_poses)
    T = rbcd.round_global(Xg, rbcd.lifting_matrix(meta, jnp.float64))
    # Private measurements are clean, so only the frame alignment is at
    # stake — it must ignore the corrupted shared edges entirely.
    assert trajectory_error(T, Rs, ts) < 1e-6


def test_distributed_init_disconnected_raises(rng):
    meas, _ = make_measurements(rng, n=12, d=3, num_lc=0)
    part = partition_contiguous(meas, 2)
    # Remove every inter-robot edge -> robot 1 unreachable.
    r1, r2 = np.asarray(part.meas.r1), np.asarray(part.meas.r2)
    keep = r1 == r2
    m = part.meas
    sub = dataclasses.replace(
        m, r1=m.r1[keep], p1=m.p1[keep], r2=m.r2[keep], p2=m.p2[keep],
        R=m.R[keep], t=m.t[keep], kappa=m.kappa[keep], tau=m.tau[keep],
        weight=m.weight[keep], is_known_inlier=m.is_known_inlier[keep])
    part2 = dataclasses.replace(part, meas=sub)
    params = AgentParams(d=3, r=5, num_robots=2)
    graph, meta = rbcd.build_graph(part2, params.r, jnp.float64)
    with pytest.raises(ValueError, match="disconnected"):
        dist_init.distributed_initialization(part2, meta, graph, params)


def test_solve_rbcd_distributed_init_end_to_end(rng):
    # With measurement noise the MAP estimate differs from ground truth;
    # the right bar is that the distributed-init solve reaches the same
    # optimum as the centralized-chordal-init solve.
    meas, _ = make_measurements(rng, n=24, d=3, num_lc=10,
                                rot_noise=0.02, trans_noise=0.02)
    params = AgentParams(d=3, r=5, num_robots=4, schedule=Schedule.JACOBI,
                         rel_change_tol=1e-8,
                         solver=SolverParams(grad_norm_tol=1e-6))
    res = rbcd.solve_rbcd(meas, 4, params, max_iters=150, grad_norm_tol=1e-4,
                          init="distributed")
    ref = rbcd.solve_rbcd(meas, 4, params, max_iters=150, grad_norm_tol=1e-4,
                          init="chordal")
    assert res.grad_norm_history[-1] < 1e-4
    assert res.cost_history[-1] <= ref.cost_history[-1] * (1 + 1e-6) + 1e-9


def test_solve_rbcd_distributed_init_robust_odometry_start(rng):
    # Robust cost => local init is odometry propagation, not chordal
    # (reference localInitialization policy, PGOAgent.cpp:947-962), and the
    # solve must still reject outliers and converge.
    meas, (Rs, ts) = make_measurements(rng, n=24, d=3, num_lc=10,
                                       outlier_lc=4)
    params = AgentParams(
        d=3, r=5, num_robots=4, schedule=Schedule.JACOBI,
        robust=RobustCostParams(cost_type=RobustCostType.GNC_TLS,
                                gnc_barc=0.5),
        robust_opt_inner_iters=10, rel_change_tol=1e-8,
        solver=SolverParams(grad_norm_tol=1e-6))
    res = rbcd.solve_rbcd(meas, 4, params, max_iters=120, grad_norm_tol=1e-6,
                          init="distributed")
    w = np.asarray(res.weights)
    assert np.all(w[-4:] < 0.01)
    assert trajectory_error(res.T, Rs, ts) < 1e-3


def test_async_solve_kitti_se2(data_dir):
    # BASELINE config #3 territory: SE(2) kitti_00 under the ASYNC schedule
    # (the on-device analog of the reference's Poisson-clock threads) with
    # distributed initialization — truncated to keep test runtime sane.
    from dpgo_tpu.utils.g2o import read_g2o

    meas = read_g2o(f"{data_dir}/kitti_00.g2o")
    assert meas.d == 2 and meas.num_poses == 4541
    # First 2000 poses contain real loop closures (the earliest spans
    # ~130 -> ~1600), so the segment is a genuine SLAM sub-problem.
    N = 2000
    keep = (np.asarray(meas.p1) < N) & (np.asarray(meas.p2) < N)
    sub = dataclasses.replace(
        meas, num_poses=N,
        r1=meas.r1[keep], p1=meas.p1[keep], r2=meas.r2[keep], p2=meas.p2[keep],
        R=meas.R[keep], t=meas.t[keep], kappa=meas.kappa[keep],
        tau=meas.tau[keep], weight=meas.weight[keep],
        is_known_inlier=meas.is_known_inlier[keep])
    assert (np.abs(np.asarray(sub.p2) - np.asarray(sub.p1)) != 1).sum() > 0
    params = AgentParams(d=2, r=3, num_robots=4, schedule=Schedule.ASYNC,
                         async_update_prob=0.5, rel_change_tol=1e-6)
    res = rbcd.solve_rbcd(sub, 4, params, max_iters=100, grad_norm_tol=0.1,
                          init="distributed")
    assert res.cost_history[-1] < res.cost_history[0]
    assert np.isfinite(np.asarray(res.T)).all()
