"""Serving-plane throughput benchmark: batched QPS vs. sequential cold solves.

The serving acceptance measurement (ROADMAP item 1): N concurrent
mixed-size problems through the batched solve server
(``dpgo_tpu.serve``) vs. the same problems solved one at a time with
``solve_rbcd`` — the library's cold path, where every distinct problem
shape compiles and dispatches its own programs.  The batched arm pads the
problems into shape buckets and solves many per device dispatch through
the fingerprint-keyed executable cache, which is exactly the work the
sequential arm repeats per problem.

Both arms run cold in one process (the persistent XLA disk cache is
disabled below so "cold" is real on every invocation) and must agree on
final costs within ``--parity-rtol``.  Emits ONE ``metric_record`` JSON
line on stdout (the BENCH_r0*.json schema), and with ``--telemetry`` the
serve plane's per-tenant SLO events land in a run directory the report
CLI renders (``python -m dpgo_tpu.obs.report <dir>`` -> "serving"
section with QPS, occupancy, and p50/p99 latency).

Usage::

    JAX_PLATFORMS=cpu python bench_serving.py --n-problems 8 \
        --telemetry /tmp/serve_bench_run
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

# Cold means cold: a warm persistent compile cache would hide exactly the
# per-shape compilation cost the sequential arm is supposed to pay.
os.environ.setdefault("DPGO_TPU_COMPILATION_CACHE", "0")

import jax  # noqa: E402

jax.config.update("jax_enable_x64", True)

import numpy as np  # noqa: E402

from dpgo_tpu import obs  # noqa: E402
from dpgo_tpu.config import AgentParams  # noqa: E402
from dpgo_tpu.models import rbcd  # noqa: E402
from dpgo_tpu.utils.synthetic import make_measurements  # noqa: E402


def make_problems(n_problems: int, base_n: int, spread: int, seed: int):
    """Mixed-size synthetic pose graphs: sizes fan out over ``spread``
    poses so no two problems share a raw shape (the sequential arm gets
    no accidental jit-cache reuse), while bucketing coalesces them."""
    rng = np.random.default_rng(seed)
    out = []
    for k in range(n_problems):
        n = base_n + (k * spread) // max(1, n_problems - 1)
        meas, _ = make_measurements(
            np.random.default_rng(seed + 7 * k), n=n, d=3,
            num_lc=6 + k % 5, rot_noise=0.01, trans_noise=0.01)
        out.append(meas)
    rng.shuffle(out)
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--n-problems", type=int, default=8)
    ap.add_argument("--robots", type=int, default=2)
    ap.add_argument("--base-n", type=int, default=40, help="smallest problem")
    ap.add_argument("--spread", type=int, default=14,
                    help="pose-count fan-out across problems")
    ap.add_argument("--max-iters", type=int, default=10)
    ap.add_argument("--eval-every", type=int, default=5)
    ap.add_argument("--max-batch", type=int, default=8)
    ap.add_argument("--quantum", type=int, default=64,
                    help="serve bucket quantum (coarser = fewer buckets)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--parity-rtol", type=float, default=1e-4,
                    help="required relative agreement of final costs")
    ap.add_argument("--tenants", type=int, default=2,
                    help="requests round-robin over this many tenants")
    ap.add_argument("--telemetry", metavar="DIR", default=None)
    ap.add_argument("--metrics-port", type=int, default=None,
                    help="expose live /metrics,/healthz,/statusz during the "
                         "batched arm (requires --telemetry)")
    ap.add_argument("--slo-latency-s", type=float, default=None,
                    help="per-request latency objective -> burn-rate SLO "
                         "gauges/events in the run")
    ap.add_argument("--profile-dir", metavar="DIR", default=None,
                    help="jax.profiler window over the first batches "
                         "(requires --telemetry)")
    ap.add_argument("--certified", action="store_true",
                    help="add the certified arm: the same batch with "
                         "certify_mode='device' (the dual certificate "
                         "fused into the terminal epilogue), recording "
                         "certified p50/p99 latency alongside the plain "
                         "arm's")
    ap.add_argument("--certify-eta", type=float, default=1e-5)
    args = ap.parse_args(argv)

    from dpgo_tpu.serve import ServeSLO, SolveRequest, SolveServer

    problems = make_problems(args.n_problems, args.base_n, args.spread,
                             args.seed)
    params = AgentParams(d=3, r=5, num_robots=args.robots)
    gtol = 1e-12  # run full --max-iters in both arms: equal work per problem

    def log(msg):
        print(msg, file=sys.stderr, flush=True)

    # --- Arm 1: sequential cold solves (the library path) ------------------
    log(f"[seq] {args.n_problems} problems x {args.robots} robots, "
        f"max_iters {args.max_iters}")
    t0 = time.perf_counter()
    seq_results = [
        rbcd.solve_rbcd(m, args.robots, params=params,
                        max_iters=args.max_iters, grad_norm_tol=gtol,
                        eval_every=args.eval_every)
        for m in problems
    ]
    t_seq = time.perf_counter() - t0
    qps_seq = args.n_problems / t_seq
    log(f"[seq] {t_seq:.2f}s ({qps_seq:.3f} problems/s)")

    # --- Arm 2: batched serving ------------------------------------------
    from dpgo_tpu.obs.events import metric_record

    scope = obs.run_scope(args.telemetry) if args.telemetry else None
    run = scope.__enter__() if scope else None
    try:
        t0 = time.perf_counter()
        with SolveServer(max_batch=args.max_batch, batch_window_s=0.02,
                         quantum=args.quantum,
                         slo=ServeSLO(latency_s=args.slo_latency_s)
                         if args.slo_latency_s is not None else None,
                         metrics_port=args.metrics_port,
                         profile_dir=args.profile_dir) as srv:
            if srv.sidecar is not None:
                log(f"[serve] metrics on {srv.sidecar.host}:"
                    f"{srv.sidecar.port}")
            tickets = [
                srv.submit(SolveRequest(
                    meas=m, num_robots=args.robots, params=params,
                    tenant=f"tenant{k % max(1, args.tenants)}",
                    max_iters=args.max_iters, grad_norm_tol=gtol,
                    eval_every=args.eval_every))
                for k, m in enumerate(problems)
            ]
            srv_results = [t.result(timeout=3600) for t in tickets]
            lat = [t.latency_s for t in tickets]
            cache = srv.cache.stats()
        t_batch = time.perf_counter() - t0
        qps_batch = args.n_problems / t_batch
        log(f"[serve] {t_batch:.2f}s ({qps_batch:.3f} problems/s), "
            f"cache {cache}")

        # --- Parity -------------------------------------------------------
        worst = 0.0
        for a, b in zip(seq_results, srv_results):
            ca, cb = a.cost_history[-1], b.cost_history[-1]
            rel = abs(ca - cb) / max(1.0, abs(ca))
            worst = max(worst, rel)
            if rel > args.parity_rtol:
                log(f"PARITY FAIL: sequential {ca} vs batched {cb} "
                    f"(rel {rel})")
                return 1
        log(f"[parity] worst relative final-cost diff {worst:.3g}")

        lat = sorted(x for x in lat if x is not None)
        p50 = lat[len(lat) // 2] if lat else None
        p99 = lat[min(len(lat) - 1, int(round(0.99 * (len(lat) - 1))))] \
            if lat else None

        # --- Arm 3 (--certified): the same batch, certified replies ------
        cert_fields = {}
        if args.certified:
            import dataclasses as _dc

            params_c = _dc.replace(params, certify_mode="device",
                                   certify_eta=args.certify_eta)
            t0 = time.perf_counter()
            with SolveServer(max_batch=args.max_batch, batch_window_s=0.02,
                             quantum=args.quantum) as srv_c:
                tickets_c = [
                    srv_c.submit(SolveRequest(
                        meas=m, num_robots=args.robots, params=params_c,
                        tenant=f"tenant{k % max(1, args.tenants)}",
                        max_iters=args.max_iters, grad_norm_tol=gtol,
                        eval_every=args.eval_every))
                    for k, m in enumerate(problems)
                ]
                cert_results = [t.result(timeout=3600) for t in tickets_c]
                lat_c = sorted(t.latency_s for t in tickets_c
                               if t.latency_s is not None)
            t_cert = time.perf_counter() - t0
            certs = [r.certificate for r in cert_results]
            if any(c is None for c in certs):
                log("CERTIFIED ARM FAIL: a result came back without a "
                    "certificate")
                return 1
            n_acc = sum(bool(c.certified) for c in certs)
            cp50 = lat_c[len(lat_c) // 2] if lat_c else None
            cp99 = lat_c[min(len(lat_c) - 1,
                             int(round(0.99 * (len(lat_c) - 1))))] \
                if lat_c else None
            log(f"[certified] {t_cert:.2f}s "
                f"({args.n_problems / t_cert:.3f} problems/s), "
                f"{n_acc}/{len(certs)} accepted, p99 "
                f"{cp99 if cp99 is not None else float('nan'):.4f}s")
            cert_fields = dict(
                certified_qps=round(args.n_problems / t_cert, 4),
                certified_latency_p50_s=round(cp50, 4)
                if cp50 is not None else None,
                certified_latency_p99_s=round(cp99, 4)
                if cp99 is not None else None,
                certified_accepted=n_acc,
                certified_total=len(certs),
                certify_eta=args.certify_eta,
            )

        rec = metric_record(
            "serving_batched_qps",
            round(qps_batch, 4),
            "problems/s",
            n_problems=args.n_problems,
            robots=args.robots,
            sequential_qps=round(qps_seq, 4),
            speedup_vs_sequential=round(qps_batch / qps_seq, 3),
            latency_p50_s=round(p50, 4) if p50 is not None else None,
            latency_p99_s=round(p99, 4) if p99 is not None else None,
            parity_worst_rel=float(f"{worst:.3g}"),
            cache_compiles=cache["compiles"],
            cache_hits=cache["hits"],
            max_batch=args.max_batch,
            quantum=args.quantum,
            **cert_fields,
        )
        if run is not None:
            # The bench record rides the run's event stream too, so the
            # report CLI and the regression gate see it alongside the
            # per-tenant serving SLOs.
            run.metric(rec["metric"], rec["value"], rec.get("unit"),
                       phase="bench",
                       **{k: v for k, v in rec.items()
                          if k not in ("metric", "value", "unit")})
    finally:
        if scope:
            scope.__exit__(None, None, None)
    print(json.dumps(rec), flush=True)

    if args.telemetry:
        # The batched arm ran traced (admission -> queue -> dispatch ->
        # reply spans with batch-mate flow arrows): export the Perfetto
        # timeline next to the run artifacts.
        from dpgo_tpu.obs import timeline
        from dpgo_tpu.obs.report import render_report

        trace_path = timeline.write_chrome_trace(
            os.path.join(args.telemetry, "trace.json"),
            timeline.merge([args.telemetry]))
        log(f"[bench_serving] Perfetto timeline: {trace_path}")
        log(render_report(args.telemetry))
    return 0


if __name__ == "__main__":
    sys.exit(main())
