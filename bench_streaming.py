"""Streaming-solve benchmark: warm restart after +5% new edges vs. a cold
re-solve.

The elastic-session acceptance measurement (ROADMAP item 3): a converged
live problem absorbs a batch of streamed edges (+5% of the measurement
count by default) and re-solves two ways —

* **cold** — the library path a streaming-less stack pays every time new
  measurements land: ``solve_rbcd`` on the full measurement set (problem
  build, fresh compile of the unpadded shapes, centralized chordal init,
  full descent).  The persistent XLA compile cache is disabled below, so
  cold is real.
* **warm** — ``LiveProblem.warm_dispatch``: the edge batch lands as masked
  appends into the padded bucket layout (no shape change, so every
  compiled program is reused), and the solve resumes from the previous
  terminal ``RBCDState`` instead of the chordal init.

Both arms run to the block fixed point (``rel_change_tol=0`` +
near-zero gradient tolerance), so the final costs must agree to
``--parity-rtol`` (default 1e-6) — the warm path must buy SPEED, never a
different answer.  Emits ONE ``metric_record`` JSON line (the
``BENCH_r0*.json`` schema) with the wall-clock ratio the CI smoke gates
at ``warm <= 0.25 x cold``.

Usage::

    JAX_PLATFORMS=cpu python bench_streaming.py --n 60 --extra-frac 0.05
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys
import time

# Cold means cold: the sequential arm must pay its own compilation.
os.environ.setdefault("DPGO_TPU_COMPILATION_CACHE", "0")

import jax  # noqa: E402

jax.config.update("jax_enable_x64", True)

import numpy as np  # noqa: E402

from dpgo_tpu import obs  # noqa: E402
from dpgo_tpu.obs.events import metric_record  # noqa: E402
from dpgo_tpu.config import AgentParams  # noqa: E402
from dpgo_tpu.models import rbcd  # noqa: E402
from dpgo_tpu.models.incremental import LiveProblem  # noqa: E402
from dpgo_tpu.types import loop_closure_mask  # noqa: E402
from dpgo_tpu.utils.synthetic import make_measurements  # noqa: E402


def split_stream(n, num_lc, extra_frac, seed, noise):
    """Full problem + (base, streamed-extra) split over a FIXED pose set:
    the stream is the newest ``extra_frac`` of the loop closures."""
    rng = np.random.default_rng(seed)
    meas, _ = make_measurements(rng, n=n, d=3, num_lc=num_lc,
                                rot_noise=noise, trans_noise=noise)
    lc_idx = np.nonzero(loop_closure_mask(meas))[0]
    n_extra = max(1, int(round(extra_frac * len(meas))))
    keep = np.ones(len(meas), bool)
    keep[lc_idx[-n_extra:]] = False
    base = dataclasses.replace(meas.select(keep), num_poses=meas.num_poses)
    extra = dataclasses.replace(meas.select(~keep),
                                num_poses=meas.num_poses)
    return meas, base, extra


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--n", type=int, default=60, help="poses")
    ap.add_argument("--robots", type=int, default=3)
    ap.add_argument("--num-lc", type=int, default=30)
    ap.add_argument("--extra-frac", type=float, default=0.05,
                    help="streamed fraction of the measurement count")
    ap.add_argument("--noise", type=float, default=0.02)
    ap.add_argument("--max-iters", type=int, default=400)
    ap.add_argument("--eval-every", type=int, default=2)
    ap.add_argument("--seed", type=int, default=1)
    ap.add_argument("--parity-rtol", type=float, default=1e-6,
                    help="required relative agreement of final costs")
    ap.add_argument("--telemetry", metavar="DIR", default=None)
    args = ap.parse_args(argv)

    meas, base, extra = split_stream(args.n, args.num_lc, args.extra_frac,
                                     args.seed, args.noise)
    # Fixed-point termination: consensus at rel_change 0 (the inner
    # solver's early exit), gradient gate effectively off — both arms
    # converge to the same optimum, making the 1e-6 parity meaningful.
    params = AgentParams(d=3, r=5, num_robots=args.robots,
                         rel_change_tol=0.0)
    gtol = 1e-9

    def log(msg):
        print(msg, file=sys.stderr, flush=True)

    scope = obs.run_scope(args.telemetry) if args.telemetry else None
    run = scope.__enter__() if scope else None
    try:
        # --- session setup: solve the base problem (padded bucket) --------
        live = LiveProblem(base, args.robots, params=params)
        log(f"[base] {len(base)} edges, bucket {tuple(live.shape)}")
        t0 = time.perf_counter()
        res0 = live.solve(max_iters=args.max_iters, grad_norm_tol=gtol,
                          eval_every=args.eval_every)
        t_base = time.perf_counter() - t0
        log(f"[base] {res0.iterations} rounds in {t_base:.2f}s "
            f"({res0.terminated_by})")

        # --- cold arm: the library path on the grown problem --------------
        log(f"[cold] solve_rbcd on {len(meas)} edges "
            f"(+{len(extra)} streamed)")
        t0 = time.perf_counter()
        resc = rbcd.solve_rbcd(meas, args.robots, params=params,
                               max_iters=args.max_iters,
                               grad_norm_tol=gtol,
                               eval_every=args.eval_every)
        t_cold = time.perf_counter() - t0
        log(f"[cold] {resc.iterations} rounds in {t_cold:.2f}s")

        # --- warm arm: delta apply + resume from the terminal state -------
        t0 = time.perf_counter()
        resw = live.warm_dispatch(res0, new_edges=extra,
                                  max_iters=args.max_iters,
                                  grad_norm_tol=gtol,
                                  eval_every=args.eval_every)
        t_warm = time.perf_counter() - t0
        delta_mode = live.last_delta.mode if live.last_delta else "none"
        log(f"[warm] {resw.iterations} rounds in {t_warm:.2f}s "
            f"(delta mode {delta_mode})")

        rel = abs(resw.cost_history[-1] - resc.cost_history[-1]) / \
            max(1.0, abs(resc.cost_history[-1]))
        if rel > args.parity_rtol:
            log(f"PARITY FAIL: cold {resc.cost_history[-1]} vs warm "
                f"{resw.cost_history[-1]} (rel {rel})")
            return 1
        ratio = t_warm / t_cold
        log(f"[streaming] warm/cold wall {ratio:.3f} "
            f"(cold {t_cold:.2f}s, warm {t_warm:.2f}s), parity rel "
            f"{rel:.3g}")

        rec = metric_record(
            "streaming_warm_cold_ratio",
            round(ratio, 4),
            "x",
            n_poses=args.n,
            robots=args.robots,
            edges_base=len(base),
            edges_streamed=len(extra),
            extra_frac=args.extra_frac,
            mode=delta_mode,
            t_cold_s=round(t_cold, 4),
            t_warm_s=round(t_warm, 4),
            rounds_cold=resc.iterations,
            rounds_warm=resw.iterations,
            parity_rel=float(f"{rel:.3g}"),
            final_cost=resw.cost_history[-1],
        )
        if run is not None:
            run.metric(rec["metric"], rec["value"], rec.get("unit"),
                       phase="bench",
                       **{k: v for k, v in rec.items()
                          if k not in ("metric", "value", "unit")})
    finally:
        if scope:
            scope.__exit__(None, None, None)
    print(json.dumps(rec), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
