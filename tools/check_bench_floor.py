"""Bench non-regression gate (ISSUE 9 CI satellite; MULTICHIP schema
added by ISSUE 11).

Reads one bench record JSON (a file argument, or stdin), auto-detects its
kind, and enforces:

For a ``bench.py`` kernel record:

1. Record schema — the fields every consumer (BENCH_r0*.json trajectory,
   obs report, regress gate) relies on must be present and sane on EVERY
   platform, so a CPU-only CI runner still catches a bench.py refactor
   that breaks the record.
2. The accelerator floor — applied only to accelerator records
   (``loop == "verdict_word"``; the CPU fallback measures a different
   arm and machine class):
     * rounds/s >= BENCH_FLOOR_ROUNDS_PER_S (default 1146, the round-5
       BENCH_r05 reading — the no-worse-than-last-round band),
     * kernel parity <= 7.7e-6 (the standing Mosaic-vs-XLA guard),
     * verdict cadence K >= 4 and measured host_syncs_per_100_rounds
       <= 100/K (one word fetch per K rounds, the readback-kill
       acceptance).

For a ``bench_sharded.py`` MULTICHIP record (``record == "MULTICHIP"``):
the weak-scaling arm is present, device counts ascend, every arm carries
positive rounds/s + poses/s, the sharded verdict cadence keeps host
syncs at <= 100/K, the overlap A/B and GN-tail parity blocks are sane
(tail parity <= 1e-6 when the arm ran), a scale_test block (when
present) actually completed through the sharded verdict path, and a
resilience block (the ISSUE-14 chaos arm, when present and not skipped)
recovered at least once, matched the fault-free cost within
RESILIENCE_MAX_COST_REL (default 1e-2), and kept the recovery overhead
under RESILIENCE_MAX_RECOVERY_S (default 120s per recovery).

For a ``bench_serving.py`` serving record (``metric ==
"serving_batched_qps"``): positive QPS and a sane speedup field; when
the ``--certified`` arm ran, every request came back with a
certificate and the certified p99 latency is under
SERVING_CERTIFIED_P99_S (default 120 s — the functional CPU-CI band;
tighten via env on accelerator runners).

For a ``bench_fleet.py`` FLEET record (``record == "FLEET"``; ISSUE 13):
the QPS arms ascend in replica count with positive QPS, throughput
scales >= FLEET_MIN_SCALING (default 1.7) from 1 to 2 replicas, the
chaos soak lost ZERO sessions while migrating at least one ticket and
autoscaling at least once, and the cold-start arm served its warm first
solve with serve_compile_seconds_total exactly 0 (disk hits only — XLA
never ran on the restarted replica).  Records whose soak carried the
resource-sampled flat-memory gate (``rss_flat``, ISSUE 20) must report
it true with the per-series detail attached.

For a perf-ledger record (``record == "LEDGER"``; the ``report
--ledger --json`` output, ISSUE 16): every row carries the normalized
schema (family/round/file/ok/metric/value/unit/extras), rounds ascend
without duplicates within each family, and at least one round produced
a real reading (a ledger of nothing but placeholders is a wiring bug).

Exit 0 on pass, 1 on any violation, 2 on an unreadable record.
"""
from __future__ import annotations

import json
import os
import sys

FLOOR = float(os.environ.get("BENCH_FLOOR_ROUNDS_PER_S", "1146"))
FLEET_MIN_SCALING = float(os.environ.get("FLEET_MIN_SCALING", "1.7"))
PARITY_BOUND = float(os.environ.get("BENCH_PARITY_BOUND", "7.7e-6"))
MIN_VERDICT_K = int(os.environ.get("BENCH_MIN_VERDICT_K", "4"))
GN_TAIL_PARITY_BOUND = float(
    os.environ.get("BENCH_GN_TAIL_PARITY_BOUND", "1e-6"))
RESILIENCE_MAX_RECOVERY_S = float(
    os.environ.get("RESILIENCE_MAX_RECOVERY_S", "120"))
RESILIENCE_MAX_COST_REL = float(
    os.environ.get("RESILIENCE_MAX_COST_REL", "1e-2"))
SERVING_CERTIFIED_P99_S = float(
    os.environ.get("SERVING_CERTIFIED_P99_S", "120"))


def fail(msg: str) -> None:
    print(f"bench floor gate: FAIL — {msg}")
    sys.exit(1)


def _num(x) -> bool:
    return isinstance(x, (int, float)) and not isinstance(x, bool)


def check_multichip(rec: dict) -> None:
    """MULTICHIP-record schema gate (``bench_sharded.py`` output)."""
    for key in ("n_devices", "ok", "backend", "weak_scaling",
                "verdict_every", "host_syncs_per_100_rounds", "overlap"):
        if key not in rec:
            fail(f"MULTICHIP record missing {key!r}: {sorted(rec)}")
    if not (isinstance(rec["n_devices"], int) and rec["n_devices"] >= 1):
        fail(f"bad n_devices {rec['n_devices']!r}")
    if rec["ok"] is not True:
        fail(f"record reports ok={rec['ok']!r}")
    ws = rec["weak_scaling"]
    if not (isinstance(ws, list) and ws):
        fail("empty weak_scaling arm")
    prev = 0
    for arm in ws:
        for key in ("devices", "num_robots", "n_poses", "rounds_per_s",
                    "poses_per_s"):
            if not _num(arm.get(key)) or arm[key] <= 0:
                fail(f"weak_scaling arm field {key!r} bad: {arm}")
        if arm["devices"] <= prev:
            fail(f"weak_scaling device counts must ascend: {ws}")
        prev = arm["devices"]
    k = rec["verdict_every"]
    syncs = rec["host_syncs_per_100_rounds"]
    if not (isinstance(k, int) and k >= 1):
        fail(f"verdict_every={k!r}")
    if not _num(syncs) or syncs > 100.0 / k + 1e-9:
        fail(f"host_syncs_per_100_rounds={syncs!r} > 100/K={100.0 / k:.4g}")
    ov = rec["overlap"]
    for key in ("efficiency", "overlap_rounds_per_s",
                "lockstep_rounds_per_s"):
        if not _num(ov.get(key)):
            fail(f"overlap block field {key!r} bad: {ov}")
    tail = rec.get("gn_tail")
    if tail and not tail.get("skipped"):
        if not _num(tail.get("parity_rel")) \
                or tail["parity_rel"] > GN_TAIL_PARITY_BOUND:
            fail(f"gn_tail parity {tail.get('parity_rel')!r} exceeds "
                 f"{GN_TAIL_PARITY_BOUND}")
    scale = rec.get("scale_test")
    if scale and not scale.get("skipped"):
        if scale.get("completed") is not True:
            fail(f"scale_test did not complete: {scale}")
        for key in ("n_poses", "num_robots", "rounds"):
            if not _num(scale.get(key)) or scale[key] <= 0:
                fail(f"scale_test field {key!r} bad: {scale}")
        # The certified row (ISSUE 15): a real device-certificate verdict
        # on the GN-polished terminal iterate.  The gate is schema-level
        # (a refused/failed verdict on a few functional rounds is an
        # honest reading, not a regression); a malformed payload is not.
        if "cert_status" in scale:
            if scale["cert_status"] not in ("accept", "refuse", "fail",
                                            "none"):
                fail(f"scale_test cert_status bad: {scale['cert_status']!r}")
            import math

            if not _num(scale.get("cert_lambda_min")) \
                    or not math.isfinite(scale["cert_lambda_min"]):
                fail(f"scale_test cert_lambda_min bad: "
                     f"{scale.get('cert_lambda_min')!r}")
    rz = rec.get("resilience")
    if rz and not rz.get("skipped"):
        # The chaos arm injected a fault on purpose: zero recoveries
        # means the injector/supervisor wiring is dead, not that the
        # mesh was lucky.
        if not _num(rz.get("recoveries")) or rz["recoveries"] < 1:
            fail(f"resilience arm recorded no recoveries: {rz}")
        if not _num(rz.get("final_cost_rel_err")) \
                or rz["final_cost_rel_err"] > RESILIENCE_MAX_COST_REL:
            fail(f"resilience final cost off by "
                 f"{rz.get('final_cost_rel_err')!r} "
                 f"(> {RESILIENCE_MAX_COST_REL}) vs fault-free")
        overhead = rz.get("recovery_overhead_s")
        if not _num(overhead) \
                or overhead > RESILIENCE_MAX_RECOVERY_S * rz["recoveries"]:
            fail(f"recovery overhead {overhead!r}s exceeds "
                 f"{RESILIENCE_MAX_RECOVERY_S}s per recovery "
                 f"x{rz['recoveries']}")
    print(f"bench floor gate: PASS — MULTICHIP schema ok "
          f"({rec['n_devices']} devices, {len(ws)} weak-scaling arms, "
          f"{syncs} syncs/100 rounds at K={k}"
          + (f", scale_test {scale['n_poses']} poses ok"
             if scale and not scale.get("skipped") else "")
          + (f", chaos arm {rz['recoveries']} recoveries "
             f"({rz['recovery_overhead_s']:.1f}s overhead)"
             if rz and not rz.get("skipped") else "") + ")")


def check_ledger(rec: dict) -> None:
    """LEDGER-record schema gate (``report --ledger --json`` output,
    ISSUE 16): every row is well-formed, rounds ascend without
    duplicates within each family, and the table is not all
    placeholders — at least one round produced a real reading."""
    for key in ("root", "rounds", "families", "rows"):
        if key not in rec:
            fail(f"LEDGER record missing {key!r}: {sorted(rec)}")
    rows = rec["rows"]
    if not (isinstance(rows, list) and rows):
        fail("empty ledger: no BENCH_r*/MULTICHIP_r*/FLEET_r* rows")
    if rec["rounds"] != len(rows):
        fail(f"rounds={rec['rounds']!r} != len(rows)={len(rows)}")
    families = rec["families"]
    prev: dict = {}
    readings = 0
    for row in rows:
        for key in ("family", "round", "file", "ok", "metric", "value",
                    "unit", "extras"):
            if key not in row:
                fail(f"ledger row missing {key!r}: {sorted(row)}")
        fam = row["family"]
        if fam not in ("BENCH", "MULTICHIP", "FLEET"):
            fail(f"unknown ledger family {fam!r}")
        if fam not in families:
            fail(f"row family {fam!r} absent from families {families}")
        if not (isinstance(row["round"], int) and row["round"] >= 1):
            fail(f"bad round {row['round']!r} in {row['file']!r}")
        if row["round"] <= prev.get(fam, 0):
            fail(f"{fam} rounds must ascend without duplicates: "
                 f"r{row['round']} after r{prev[fam]}")
        prev[fam] = row["round"]
        if not isinstance(row["ok"], bool):
            fail(f"non-boolean ok {row['ok']!r} in {row['file']!r}")
        if not isinstance(row["extras"], dict):
            fail(f"non-dict extras in {row['file']!r}")
        if row["value"] is not None:
            if not _num(row["value"]):
                fail(f"non-numeric value {row['value']!r} in "
                     f"{row['file']!r}")
            if not isinstance(row["metric"], str) or not row["metric"]:
                fail(f"row with a value but no metric name: "
                     f"{row['file']!r}")
            readings += 1
    if readings < 1:
        fail("ledger has rows but zero real readings (all placeholders)")
    print(f"bench floor gate: PASS — LEDGER ok ({len(rows)} rounds, "
          f"{readings} readings across {', '.join(families)})")


def check_fleet(rec: dict) -> None:
    """FLEET-record schema + scaling/chaos/cold-start gate
    (``bench_fleet.py`` output)."""
    for key in ("ok", "backend", "qps", "soak", "cold_start"):
        if key not in rec:
            fail(f"FLEET record missing {key!r}: {sorted(rec)}")
    if rec["ok"] is not True:
        fail(f"record reports ok={rec['ok']!r}")
    qps = rec["qps"]
    if not (isinstance(qps, list) and qps):
        fail("empty qps arm")
    prev = 0
    for arm in qps:
        for key in ("replicas", "qps"):
            if not _num(arm.get(key)) or arm[key] <= 0:
                fail(f"qps arm field {key!r} bad: {arm}")
        if arm["replicas"] <= prev:
            fail(f"qps replica counts must ascend: {qps}")
        prev = arm["replicas"]
    scaling = rec.get("scaling_1_to_2")
    if scaling is not None:
        if not _num(scaling) or scaling < FLEET_MIN_SCALING:
            fail(f"1->2 replica scaling {scaling!r} < required "
                 f"{FLEET_MIN_SCALING}")
    elif {a["replicas"] for a in qps} >= {1, 2}:
        fail("qps arms cover 1 and 2 replicas but scaling_1_to_2 missing")
    soak = rec["soak"]
    oop = bool(rec.get("out_of_process"))
    if not soak.get("skipped"):
        if soak.get("lost") != 0:
            fail(f"soak lost sessions: {soak}")
        if not _num(soak.get("migrations")) or soak["migrations"] < 1:
            fail(f"soak recorded no migrations: {soak}")
        if not _num(soak.get("scale_ups")) or soak["scale_ups"] < 1:
            fail(f"soak recorded no autoscale-up: {soak}")
        if oop:
            # Out-of-process soak: the kill was a real SIGKILL of a
            # replica OS process — the manager must have respawned one.
            if not _num(soak.get("respawns")) or soak["respawns"] < 1:
                fail(f"out-of-process soak recorded no respawn after the "
                     f"SIGKILL: {soak}")
            if not soak.get("killed"):
                fail(f"out-of-process soak names no killed replica: {soak}")
        # Resource-sampled soaks (ISSUE 20) carry the flat-memory gate:
        # a record claiming the soak passed while its own RSS series
        # regressed is a contradiction, not a pass.  Older records
        # without the field pass unchanged.
        if "rss_flat" in soak:
            if soak["rss_flat"] is not True:
                fail(f"soak RSS series regressed (rss_flat false): "
                     f"{soak.get('rss_gate')}")
            if not isinstance(soak.get("rss_gate"), dict):
                fail(f"soak rss_flat present without rss_gate detail: "
                     f"{sorted(soak)}")
    cold = rec["cold_start"]
    if not cold.get("skipped"):
        if cold.get("compile_seconds_total") != 0:
            fail("restarted replica spent "
                 f"{cold.get('compile_seconds_total')!r}s in XLA "
                 "(persistent AOT cache must make it exactly 0)")
        if not _num(cold.get("disk_hits")) or cold["disk_hits"] < 1:
            fail(f"cold-start arm shows no disk hits: {cold}")
    print("bench floor gate: PASS — FLEET ok ("
          + ("out-of-process, " if oop else "")
          + ", ".join(f"{a['replicas']}r={a['qps']}/s" for a in qps)
          + (f", scaling {scaling}" if scaling is not None else "")
          + ("" if soak.get("skipped") else
             f", soak lost={soak['lost']} migrations={soak['migrations']} "
             f"scale_ups={soak['scale_ups']}")
          + ("" if cold.get("skipped") else
             f", warm restart compile_s={cold['compile_seconds_total']} "
             f"disk_hits={cold['disk_hits']}") + ")")


def check_serving(rec: dict) -> None:
    """Serving-record gate (``bench_serving.py`` output), including the
    ``--certified`` p99 arm when present."""
    for key in ("value", "unit", "n_problems", "sequential_qps",
                "speedup_vs_sequential", "latency_p99_s"):
        if key not in rec:
            fail(f"serving record missing {key!r}: {sorted(rec)}")
    if not _num(rec["value"]) or rec["value"] <= 0:
        fail(f"non-positive batched QPS {rec['value']!r}")
    if not _num(rec["speedup_vs_sequential"]):
        fail(f"bad speedup_vs_sequential {rec['speedup_vs_sequential']!r}")
    cert_line = ""
    if "certified_latency_p99_s" in rec:
        p99 = rec["certified_latency_p99_s"]
        total, acc = rec.get("certified_total"), rec.get("certified_accepted")
        if not _num(p99) or p99 <= 0:
            fail(f"certified arm p99 bad: {p99!r}")
        if p99 > SERVING_CERTIFIED_P99_S:
            fail(f"certified p99 {p99}s exceeds floor "
                 f"{SERVING_CERTIFIED_P99_S}s")
        if not _num(total) or total != rec["n_problems"]:
            fail(f"certified arm covered {total!r} of "
                 f"{rec['n_problems']} requests")
        if not _num(acc) or acc < 0 or acc > total:
            fail(f"certified_accepted bad: {acc!r}/{total!r}")
        cert_line = (f", certified p99 {p99}s <= {SERVING_CERTIFIED_P99_S}s "
                     f"({acc}/{total} accepted)")
    print(f"bench floor gate: PASS — serving {rec['value']} problems/s "
          f"(speedup {rec['speedup_vs_sequential']}x, "
          f"p99 {rec['latency_p99_s']}s{cert_line})")


def main() -> None:
    try:
        if len(sys.argv) > 1:
            with open(sys.argv[1]) as f:
                text = f.read()
        else:
            text = sys.stdin.read()
        # Checked-in records are whole-file (pretty-printed) JSON; bench
        # stdout prints exactly one JSON line last — tolerate log lines
        # by falling back to the final line.
        try:
            rec = json.loads(text)
        except ValueError:
            rec = json.loads(text.strip().splitlines()[-1])
    except (OSError, ValueError, IndexError) as e:
        print(f"bench floor gate: unreadable record ({e})")
        sys.exit(2)

    if rec.get("record") == "MULTICHIP":
        check_multichip(rec)
        return

    if rec.get("record") == "FLEET":
        check_fleet(rec)
        return

    if rec.get("record") == "LEDGER":
        check_ledger(rec)
        return

    if rec.get("metric") == "serving_batched_qps":
        check_serving(rec)
        return

    # 1. Schema (all platforms).
    for key in ("metric", "value", "unit", "vs_baseline", "cpu_arm_band",
                "loop", "fused_rounds_per_s"):
        if key not in rec:
            fail(f"record missing {key!r}: {sorted(rec)}")
    if rec["metric"] != "rbcd_rounds_per_sec_sphere2500_8agents_r5":
        fail(f"unexpected metric {rec['metric']!r}")
    if not (isinstance(rec["value"], (int, float)) and rec["value"] > 0):
        fail(f"non-positive value {rec['value']!r}")
    band = rec["cpu_arm_band"]
    if not (band["min"] <= band["median"] <= band["max"]):
        fail(f"malformed cpu_arm_band {band}")

    # 2. Accelerator floor.
    if rec["loop"] != "verdict_word":
        print(f"bench floor gate: schema ok; floor skipped "
              f"(loop={rec['loop']!r} — CPU fallback arm, "
              f"{rec['value']} {rec['unit']})")
        return
    if rec["value"] < FLOOR:
        fail(f"{rec['value']} rounds/s < floor {FLOOR}")
    parity = rec.get("kernel_parity_max_abs_diff")
    if parity is None or parity > PARITY_BOUND:
        fail(f"kernel parity {parity} exceeds bound {PARITY_BOUND}")
    k = rec.get("verdict_every")
    syncs = rec.get("host_syncs_per_100_rounds")
    if not (isinstance(k, int) and k >= MIN_VERDICT_K):
        fail(f"verdict_every={k!r} < required {MIN_VERDICT_K}")
    if syncs is None or syncs > 100.0 / k + 1e-9:
        fail(f"host_syncs_per_100_rounds={syncs!r} > 100/K={100.0 / k:.4g}")
    print(f"bench floor gate: PASS — {rec['value']} rounds/s >= {FLOOR}, "
          f"parity {parity:.2e} <= {PARITY_BOUND:.1e}, "
          f"{syncs} syncs/100 rounds at K={k}")


if __name__ == "__main__":
    main()
