"""Developer tooling for the dpgo_tpu repository (not shipped with the
package).  ``tools.dpgolint`` is the project-invariant static analyzer."""
