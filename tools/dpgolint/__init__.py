"""dpgolint — project-invariant static analysis for dpgo_tpu.

Five AST passes encode the invariants the hand-written boom tests only
spot-check (see docs/ARCHITECTURE.md, "Static analysis & invariants"):

* **DPG001 jit-purity** — no clocks/RNGs/prints/host-syncs/global
  mutation in code reachable from jit entry points.
* **DPG002 telemetry-fence** — obs-owned constructors dominated by a
  telemetry-enabled guard.
* **DPG003 host-sync-hazard** — no implicit device->host transfers in
  hot-path loops outside the sanctioned readback seams.
* **DPG004 lock-discipline** — ``# guarded-by:`` attributes touched only
  under their lock, ``# holds:`` helpers called only under it,
  consistent lock order.
* **DPG005 wire-schema-symmetry** — packed and unpacked frame keys
  match in both codecs.

Usage: ``python -m tools.dpgolint [paths...] [--format json]``; library
entry point ``run_lint(paths, config)``.
"""

from . import rules  # noqa: F401  (importing registers every pass)
from .config import Config, project_config
from .core import REGISTRY, Finding, Module, Rule, register, run_lint

__all__ = [
    "Config",
    "Finding",
    "Module",
    "REGISTRY",
    "Rule",
    "project_config",
    "register",
    "run_lint",
]
