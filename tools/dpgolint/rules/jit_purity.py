"""DPG001: functions reachable from jit entry points must be pure.

The fused RBCD segments are replayed bit-for-bit by the flight recorder
and cached as batched executables by the serving plane — both break the
moment traced code consults the host (wall clocks, Python RNGs, prints,
``.item()``/``float()`` materializations) or mutates state outside its
arguments.  jax would catch *some* of these at trace time with a
``TracerError``; this pass catches all of them at review time, including
the ones jit silently constant-folds (``time.time()`` evaluated once at
trace time is the classic silent version skew).

Entry points are discovered, not declared: any function passed to
``jax.jit``/``jax.vmap``/``jax.pmap`` (as a call argument, through
``functools.partial``, or as a decorator) plus the configured
``extra_entries``.  Reachability follows same-module calls by name —
cross-module purity is each callee module's own lint run.
"""

from __future__ import annotations

import ast

from ..core import (Module, Rule, dotted_name, register,
                    walk_skipping_functions)

_JIT_WRAPPERS = {"jit", "vmap", "pmap", "shard_map", "grad", "value_and_grad",
                 "checkpoint", "remat", "custom_jvp", "custom_vjp"}


def _import_table(tree: ast.AST) -> dict[str, str]:
    """local alias -> imported module/object full name."""
    table: dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                table[a.asname or a.name.split(".")[0]] = a.name
        elif isinstance(node, ast.ImportFrom) and node.module:
            for a in node.names:
                table[a.asname or a.name] = f"{node.module}.{a.name}"
    return table


def _is_jit_wrapper(call: ast.Call, imports: dict[str, str]) -> bool:
    name = dotted_name(call.func)
    if name is None:
        return False
    parts = name.split(".")
    head = imports.get(parts[0], parts[0])
    full = ".".join([head] + parts[1:])
    last = full.split(".")[-1]
    return last in _JIT_WRAPPERS and ("jax" in full or full == last)


def _collect_entry_names(tree: ast.AST, imports: dict[str, str]) -> set[str]:
    entries: set[str] = set()

    def harvest(expr: ast.AST) -> None:
        """Function references inside a jit-wrapper call's arguments."""
        if isinstance(expr, (ast.Name, ast.Attribute)):
            name = dotted_name(expr)
            if name:
                entries.add(name.split(".")[-1])
        elif isinstance(expr, ast.Call):
            # jax.jit(jax.vmap(f)) / partial(jax.jit, ...)(f): recurse.
            for a in expr.args:
                harvest(a)
        elif isinstance(expr, ast.Lambda):
            entries.add(f"<lambda:{expr.lineno}>")

    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and _is_jit_wrapper(node, imports):
            for a in node.args:
                harvest(a)
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                target = dec
                if isinstance(dec, ast.Call):  # @partial(jax.jit, ...)
                    inner = [a for a in dec.args
                             if isinstance(a, (ast.Name, ast.Attribute))]
                    fname = dotted_name(dec.func) or ""
                    if fname.split(".")[-1] == "partial" and inner:
                        target = inner[0]
                    else:
                        target = dec.func
                name = dotted_name(target)
                if name is None:
                    continue
                parts = name.split(".")
                head = imports.get(parts[0], parts[0])
                full = ".".join([head] + parts[1:])
                if full.split(".")[-1] in _JIT_WRAPPERS and "jax" in full:
                    entries.add(node.name)
    return entries


def _function_defs(tree: ast.AST) -> dict[str, list[ast.AST]]:
    """Every def/assigned-lambda in the module by simple name (nested
    included — the call graph resolves by name, shadowing be damned; a
    false edge only widens the checked set)."""
    defs: dict[str, list[ast.AST]] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs.setdefault(node.name, []).append(node)
        elif isinstance(node, ast.Assign) and isinstance(node.value,
                                                         ast.Lambda):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    defs.setdefault(t.id, []).append(node.value)
        elif isinstance(node, ast.Lambda):
            defs.setdefault(f"<lambda:{node.lineno}>", []).append(node)
    return defs


def _called_names(fn: ast.AST) -> set[str]:
    names: set[str] = set()
    body = fn.body if isinstance(fn.body, list) else [fn.body]
    for stmt in body:
        for node in ast.walk(stmt):
            if isinstance(node, ast.Call):
                name = dotted_name(node.func)
                if name:
                    names.add(name.split(".")[-1])
            elif isinstance(node, (ast.Name, ast.Attribute)):
                # Functions passed by reference (e.g. to lax.scan/vmap
                # inside the entry) count as potential callees.
                name = dotted_name(node)
                if name:
                    names.add(name.split(".")[-1])
    return names


@register
class JitPurityRule(Rule):
    id = "DPG001"
    name = "jit-purity"
    invariant = ("code reachable from jax.jit/vmap/fused-segment entry "
                 "points performs no host I/O, clock/RNG reads, host "
                 "syncs, or global/closure mutation")

    def check(self, module: Module, config) -> list:
        opts = config.rule_options(self.id)
        imports = _import_table(module.tree)
        entries = _collect_entry_names(module.tree, imports)
        entries |= set(opts.get("extra_entries", []))
        defs = _function_defs(module.tree)

        # Reachability: BFS over same-module calls by simple name.
        reach: dict[str, str] = {}  # def name -> entry that reaches it
        queue = [(e, e) for e in sorted(entries) if e in defs]
        while queue:
            name, entry = queue.pop()
            if name in reach:
                continue
            reach[name] = entry
            for fn in defs[name]:
                for callee in sorted(_called_names(fn)):
                    if callee in defs and callee not in reach:
                        queue.append((callee, entry))

        findings = []
        checked: set[int] = set()
        for name, entry in sorted(reach.items()):
            for fn in defs[name]:
                if id(fn) in checked:
                    continue
                checked.add(id(fn))
                findings.extend(
                    self._check_body(module, fn, name, entry, imports))
        return findings

    def _check_body(self, module: Module, fn: ast.AST, name: str,
                    entry: str, imports: dict[str, str]) -> list:
        out = []
        where = (f"in jit-reachable function {name!r} "
                 f"(reached from entry {entry!r})")
        body = fn.body if isinstance(fn.body, list) else [fn.body]
        for stmt in body:
            for node in (stmt, *walk_skipping_functions(stmt)):
                if isinstance(node, (ast.Global, ast.Nonlocal)):
                    kind = ("global" if isinstance(node, ast.Global)
                            else "nonlocal")
                    out.append(self.finding(
                        module, node,
                        f"{kind} mutation of {', '.join(node.names)} "
                        f"{where} — jit-traced code must be pure"))
                    continue
                if not isinstance(node, ast.Call):
                    continue
                cname = dotted_name(node.func)
                if cname is None:
                    if isinstance(node.func, ast.Attribute) and \
                            node.func.attr == "item":
                        out.append(self.finding(
                            module, node,
                            f".item() host sync {where}"))
                    continue
                parts = cname.split(".")
                root = imports.get(parts[0], parts[0])
                full = ".".join([root] + parts[1:])
                if full.split(".")[0] == "time" and len(parts) > 1:
                    out.append(self.finding(
                        module, node,
                        f"wall-clock read {cname}() {where} — jit "
                        "constant-folds it at trace time"))
                elif full.split(".")[0] == "random" and len(parts) > 1:
                    out.append(self.finding(
                        module, node,
                        f"Python RNG {cname}() {where} — use jax.random "
                        "with a threaded key"))
                elif (full.startswith("numpy.random")
                      or ".random." in full and full.startswith("numpy")):
                    out.append(self.finding(
                        module, node,
                        f"numpy RNG {cname}() {where} — use jax.random "
                        "with a threaded key"))
                elif cname == "print":
                    out.append(self.finding(
                        module, node,
                        f"print() {where} — host I/O inside traced code "
                        "(use jax.debug.print for debugging)"))
                elif parts[-1] == "item" and len(parts) > 1:
                    out.append(self.finding(
                        module, node, f".item() host sync {where}"))
                elif cname == "float" and node.args and not isinstance(
                        node.args[0], ast.Constant):
                    out.append(self.finding(
                        module, node,
                        f"float() materialization {where} — forces a "
                        "device->host sync under trace"))
        return out
