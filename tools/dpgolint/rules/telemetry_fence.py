"""DPG002: obs-owned objects are only constructed behind the telemetry
fence.

The zero-overhead contract says a telemetry-off process constructs NO
observability machinery: no ``TelemetryRun``, ``HealthMonitor``,
``FlightRecorder``, ``MetricsSidecar``, ``ProfiledExecutable``,
``ProfilerWindow``, and no raw ``Span``.  The boom-patch tests prove it
for the call sites they drive; this pass proves it for every call site:
each configured constructor call must be *dominated* by a
telemetry-enabled guard —

* lexically inside the taken branch of ``if run is not None:`` /
  ``if obs.get_run() is not None:`` / ``if telemetry:`` (or the else
  branch of the negated test), where the guard variable was assigned
  from ``get_run()`` (or from ``<run> is not None``), or
* preceded, in an enclosing block, by an early exit
  ``if run is None: return/raise/continue``.

The analysis is lexical dominance, not dataflow — a guard stashed in a
helper doesn't count.  Sites where the fence is upheld by a documented
contract (obs internals whose public wrappers do the guarding) live in
``allowed_files``; anything else needs a reviewed
``# dpgolint: disable=DPG002`` with a reason.
"""

from __future__ import annotations

import ast

from ..core import Module, Rule, dotted_name, glob_match, register

DEFAULT_CONSTRUCTORS = ["TelemetryRun", "HealthMonitor", "FlightRecorder",
                        "MetricsSidecar", "ProfiledExecutable",
                        "ProfilerWindow", "Span"]


def _guard_vars(fn: ast.AST) -> set[str]:
    """Names in ``fn`` that hold the fence state: assigned from
    ``*.get_run()`` or from ``<guard> is not None``."""
    guards: set[str] = set()
    body = fn.body if isinstance(fn.body, list) else [fn.body]
    changed = True
    while changed:  # two-level chains: run = get_run(); on = run is not None
        changed = False
        for stmt in body:
            for node in ast.walk(stmt):
                if not isinstance(node, ast.Assign):
                    continue
                targets = [t.id for t in node.targets
                           if isinstance(t, ast.Name)]
                if not targets:
                    continue
                if _is_get_run(node.value) or \
                        _is_not_none_of(node.value, guards):
                    for t in targets:
                        if t not in guards:
                            guards.add(t)
                            changed = True
    return guards


def _is_get_run(expr: ast.AST) -> bool:
    if isinstance(expr, ast.Call):
        name = dotted_name(expr.func)
        return name is not None and name.split(".")[-1] == "get_run"
    return False


def _is_guard_expr(expr: ast.AST, guards: set[str]) -> bool:
    return (_is_get_run(expr)
            or (isinstance(expr, ast.Name) and expr.id in guards))


def _is_not_none_of(expr: ast.AST, guards: set[str]) -> bool:
    """``<guard> is not None``"""
    return (isinstance(expr, ast.Compare) and len(expr.ops) == 1
            and isinstance(expr.ops[0], ast.IsNot)
            and isinstance(expr.comparators[0], ast.Constant)
            and expr.comparators[0].value is None
            and _is_guard_expr(expr.left, guards))


def _is_none_of(expr: ast.AST, guards: set[str]) -> bool:
    """``<guard> is None`` or ``not <guard>``"""
    if isinstance(expr, ast.Compare) and len(expr.ops) == 1 \
            and isinstance(expr.ops[0], ast.Is) \
            and isinstance(expr.comparators[0], ast.Constant) \
            and expr.comparators[0].value is None:
        return _is_guard_expr(expr.left, guards)
    if isinstance(expr, ast.UnaryOp) and isinstance(expr.op, ast.Not):
        return _is_guard_expr(expr.operand, guards)
    return False


def _test_is_on(expr: ast.AST, guards: set[str]) -> bool:
    """A test that is true only with telemetry on."""
    if _is_not_none_of(expr, guards) or _is_guard_expr(expr, guards):
        return True
    if isinstance(expr, ast.BoolOp) and isinstance(expr.op, ast.And):
        return any(_test_is_on(v, guards) for v in expr.values)
    return False


def _exits(block: list[ast.stmt]) -> bool:
    return bool(block) and isinstance(
        block[-1], (ast.Return, ast.Raise, ast.Continue, ast.Break))


def _is_dominated(module: Module, node: ast.AST, guards: set[str]) -> bool:
    """True when every path to ``node`` passes a telemetry-on guard."""
    child = node
    for anc in module.ancestors(node):
        if isinstance(anc, ast.If):
            in_body = any(child is s or _contains(s, child)
                          for s in anc.body)
            in_orelse = any(child is s or _contains(s, child)
                            for s in anc.orelse)
            if in_body and _test_is_on(anc.test, guards):
                return True
            if in_orelse and _is_none_of(anc.test, guards):
                return True
        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.Module)):
            break
        # A Lambda defers execution but the construction still happens
        # inside the guarded dynamic extent (the cache-builder pattern):
        # keep walking outward through it.
        # Early-exit dominance: a preceding sibling `if guard is None:
        # return` in any block on the ancestor chain.
        for field in ("body", "orelse", "finalbody"):
            block = getattr(anc, field, None)
            if not isinstance(block, list):
                continue
            for i, stmt in enumerate(block):
                if stmt is child or _contains(stmt, child):
                    if _block_establishes_guard(block[:i], guards):
                        return True
                    break
        child = anc
    # Top-level statements of the enclosing (non-lambda) function.
    fn = module.enclosing_function(node)
    while isinstance(fn, ast.Lambda):
        fn = module.enclosing_function(fn)
    if fn is not None and isinstance(fn.body, list):
        for i, stmt in enumerate(fn.body):
            if stmt is node or _contains(stmt, node):
                return _block_establishes_guard(fn.body[:i], guards)
    return False


def _contains(tree: ast.AST, node: ast.AST) -> bool:
    return any(n is node for n in ast.walk(tree))


def _block_establishes_guard(prefix: list[ast.stmt],
                             guards: set[str]) -> bool:
    for stmt in prefix:
        if isinstance(stmt, ast.If) and _is_none_of(stmt.test, guards) \
                and _exits(stmt.body):
            return True
        if isinstance(stmt, ast.Assert) and _test_is_on(stmt.test, guards):
            return True
    return False


@register
class TelemetryFenceRule(Rule):
    id = "DPG002"
    name = "telemetry-fence"
    invariant = ("obs-owned constructors are dominated by a "
                 "telemetry-enabled guard (get_run() is not None)")

    def check(self, module: Module, config) -> list:
        opts = config.rule_options(self.id)
        constructors = set(opts.get("constructors", DEFAULT_CONSTRUCTORS))
        allowed = opts.get("allowed_files", [])
        if allowed and glob_match(module.relpath, allowed):
            return []
        findings = []
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            name = dotted_name(node.func)
            if name is None or name.split(".")[-1] not in constructors:
                continue
            fn = module.enclosing_function(node)
            while isinstance(fn, ast.Lambda):
                fn = module.enclosing_function(fn)
            guards = _guard_vars(fn) if fn is not None else set()
            if _is_dominated(module, node, guards):
                continue
            findings.append(self.finding(
                module, node,
                f"obs-owned construction {name}() is not dominated by a "
                "telemetry-enabled guard — telemetry-off must construct "
                "no obs objects (zero-overhead fence)"))
        return findings
