"""DPG004: annotated lock-guarded attributes are only touched under
their lock, and locks nest in one consistent order.

The serving plane, comms bus, and metrics registry are multi-threaded
(client threads, the serve worker, overlap workers, transport threads,
the HTTP sidecar).  Attributes that need a lock declare it where they are
initialized:

    self._pending: deque = deque()   # guarded-by: _cond

and helper methods that REQUIRE the lock already held declare that on
their ``def`` line:

    def _get(self, labels):   # holds: _lock

The pass then enforces, lexically, per class:

* every other load/store of ``self.<attr>`` sits inside a
  ``with self.<lock>:`` block (the declaring method — normally
  ``__init__``, where the object is not yet published — is exempt, as
  are ``holds:``-annotated methods);
* every call to a ``holds:``-annotated method is itself made under the
  lock (or from another method holding it);
* across the module, nested ``with self.<lockA>: ... with self.<lockB>:``
  acquisitions never appear in both orders (lock-order consistency by
  attribute name — the cheap static form of deadlock freedom).

``threading.Condition`` counts as a lock (its default lock is an RLock,
so re-acquiring under the same name is fine and not modeled).
"""

from __future__ import annotations

import ast
import re

from ..core import Module, Rule, register

_GUARDED_RE = re.compile(r"#\s*guarded-by:\s*([A-Za-z_]\w*)")
_HOLDS_RE = re.compile(r"#\s*holds:\s*([A-Za-z_]\w*)")


def _line_annotation(module: Module, lineno: int, rx: re.Pattern
                     ) -> str | None:
    if 1 <= lineno <= len(module.lines):
        m = rx.search(module.lines[lineno - 1])
        if m:
            return m.group(1)
    return None


def _self_attr(node: ast.AST) -> str | None:
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return node.attr
    return None


def _with_locks(module: Module, node: ast.AST) -> set[str]:
    """Lock attribute names held (lexically) at ``node``: every ancestor
    ``with self.<name>:`` (including ``.acquire()``-less Condition use)."""
    held: set[str] = set()
    for anc in module.ancestors(node):
        if isinstance(anc, ast.With):
            for item in anc.items:
                attr = _self_attr(item.context_expr)
                if attr is not None:
                    held.add(attr)
    return held


@register
class LockDisciplineRule(Rule):
    id = "DPG004"
    name = "lock-discipline"
    invariant = ("attributes declared `# guarded-by: <lock>` are only "
                 "accessed under `with self.<lock>`, helper methods "
                 "declared `# holds: <lock>` are only called under it, "
                 "and lock acquisition order is consistent")

    def check(self, module: Module, config) -> list:
        findings = []
        for node in ast.walk(module.tree):
            if isinstance(node, ast.ClassDef):
                findings.extend(self._check_class(module, node))
        findings.extend(self._check_lock_order(module))
        return findings

    # -- guarded attributes -------------------------------------------------

    def _check_class(self, module: Module, cls: ast.ClassDef) -> list:
        guarded: dict[str, str] = {}       # attr -> lock name
        declared_in: dict[str, ast.AST] = {}  # attr -> declaring method
        holds: dict[str, str] = {}         # method name -> held lock

        for node in ast.walk(cls):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                lock = _line_annotation(module, node.lineno, _HOLDS_RE)
                if lock:
                    holds[node.name] = lock
            if isinstance(node, (ast.Assign, ast.AnnAssign)):
                lock = _line_annotation(module, node.lineno, _GUARDED_RE)
                if lock is None:
                    continue
                targets = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                for t in targets:
                    attr = _self_attr(t)
                    if attr is not None:
                        guarded[attr] = lock
                        declared_in[attr] = module.enclosing_function(node)
        if not guarded and not holds:
            return []

        findings = []
        for node in ast.walk(cls):
            if isinstance(node, ast.Attribute):
                attr = _self_attr(node)
                if attr is None or attr not in guarded:
                    continue
                lock = guarded[attr]
                fn = module.enclosing_function(node)
                # Non-lambda enclosing method (nested defs — worker
                # closures — still belong to their method lexically, but
                # run on other threads, so they must lock like anyone).
                meth = fn
                while isinstance(meth, ast.Lambda):
                    meth = module.enclosing_function(meth)
                if meth is declared_in.get(attr):
                    continue  # construction happens-before publication
                if meth is not None and holds.get(meth.name) == lock:
                    continue  # caller-holds contract, checked at call sites
                if lock in _with_locks(module, node):
                    continue
                ctx = "store to" if isinstance(
                    node.ctx, (ast.Store, ast.Del)) else "read of"
                findings.append(self.finding(
                    module, node,
                    f"{ctx} self.{attr} outside `with self.{lock}` "
                    f"(declared `# guarded-by: {lock}`"
                    + (f" in {cls.name}" if cls.name else "") + ")"))
            elif isinstance(node, ast.Call):
                # Calls to holds:-annotated helpers must hold the lock.
                attr = _self_attr(node.func)
                if attr is None or attr not in holds:
                    continue
                lock = holds[attr]
                meth = module.enclosing_function(node)
                while isinstance(meth, ast.Lambda):
                    meth = module.enclosing_function(meth)
                if meth is not None and holds.get(meth.name) == lock:
                    continue
                if lock in _with_locks(module, node):
                    continue
                findings.append(self.finding(
                    module, node,
                    f"call to self.{attr}() outside `with self.{lock}` "
                    f"(declared `# holds: {lock}`)"))
        return findings

    # -- lock-order consistency --------------------------------------------

    def _check_lock_order(self, module: Module) -> list:
        edges: dict[tuple[str, str], ast.AST] = {}
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.With):
                continue
            inner = {a for item in node.items
                     if (a := _self_attr(item.context_expr)) is not None}
            if not inner:
                continue
            outer = _with_locks(module, node)
            for o in outer:
                for i in inner:
                    if o != i:
                        edges.setdefault((o, i), node)
        findings = []
        for (a, b), node in sorted(edges.items()):
            if (b, a) in edges and a < b:
                other = edges[(b, a)]
                findings.append(self.finding(
                    module, node,
                    f"inconsistent lock order: self.{a} -> self.{b} here "
                    f"but self.{b} -> self.{a} at line {other.lineno} — "
                    "pick one global order"))
        return findings
