"""Rule plugins — importing this package registers every pass."""

from . import (  # noqa: F401
    host_sync,
    jit_purity,
    lock_discipline,
    telemetry_fence,
    wire_schema,
)
