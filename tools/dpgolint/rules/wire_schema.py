"""DPG005: every wire-frame key packed has a matching unpack, and vice
versa.

The frame vocabulary (pose columns, trace context, clock stamps, agent
gossip) is an implicit schema spread across pack-side and unpack-side
functions; a key packed that nothing unpacks is dead wire bytes, and a
key unpacked that nothing packs is a silent ``None``/KeyError path that
only fires against a newer peer.  Rolling upgrades work precisely
because both codecs stay symmetric.

Per configured module, the pass collects

* **packed keys** — string keys of dict literals / dict comprehensions
  and ``frame[K] = ...`` subscript stores inside the configured
  ``pack_functions``;
* **unpacked keys** — ``frame[K]`` loads, ``.get(K)``/``.pop(K)`` calls
  (bare ``get``/``pop`` aliases included — the ``pop``-or-``get``
  dispatch idiom), ``K in frame`` tests, and ``.startswith(prefix)``
  prefix matches inside the configured ``unpack_functions``;

resolving module-level string constants (``TRACE_IDS_KEY``) and
normalizing f-strings to glob patterns (``f"{prefix}:r"`` -> ``*:r``).
Keys reduced to a bare ``*`` (fully dynamic) are ignored.  Configured
``strip_prefixes`` model re-namespacing hubs (``r{id}|...``).
"""

from __future__ import annotations

import ast
import fnmatch

from ..core import Module, Rule, dotted_name, register

_GET_NAMES = {"get", "pop"}


def _module_str_constants(tree: ast.AST) -> dict[str, str]:
    out: dict[str, str] = {}
    for node in tree.body if isinstance(tree, ast.Module) else []:
        if isinstance(node, ast.Assign) and \
                isinstance(node.value, ast.Constant) and \
                isinstance(node.value.value, str):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    out[t.id] = node.value.value
    return out


def _key_pattern(expr: ast.AST, consts: dict[str, str]) -> str | None:
    """A glob pattern for a key expression, or None when it is not
    string-like.  Dynamic parts become ``*``."""
    if isinstance(expr, ast.Constant):
        return expr.value if isinstance(expr.value, str) else None
    if isinstance(expr, ast.Name):
        return consts.get(expr.id, "*")
    if isinstance(expr, ast.JoinedStr):
        parts = []
        for v in expr.values:
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                parts.append(v.value)
            else:
                parts.append("*")
        return "".join(parts)
    if isinstance(expr, ast.BinOp) and isinstance(expr.op, ast.Add):
        left = _key_pattern(expr.left, consts)
        right = _key_pattern(expr.right, consts)
        if left is None or right is None:
            return None
        return left + right
    return None


def _functions_by_name(tree: ast.AST, names: set[str]) -> list[ast.AST]:
    return [n for n in ast.walk(tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
            and n.name in names]


def _collect_packed(fns, consts) -> dict[str, ast.AST]:
    keys: dict[str, ast.AST] = {}

    def add(pat, node):
        if pat and set(pat) != {"*"}:
            keys.setdefault(pat, node)

    for fn in fns:
        for node in ast.walk(fn):
            if isinstance(node, ast.Dict):
                for k in node.keys:
                    if k is not None:
                        add(_key_pattern(k, consts), k)
            elif isinstance(node, ast.DictComp):
                add(_key_pattern(node.key, consts), node.key)
            elif isinstance(node, ast.Subscript) and \
                    isinstance(node.ctx, ast.Store):
                add(_key_pattern(node.slice, consts), node)
    return keys


def _collect_unpacked(fns, consts) -> dict[str, ast.AST]:
    keys: dict[str, ast.AST] = {}

    def add(pat, node, prefix=False):
        if pat is None:
            return
        if prefix:
            pat = pat + "*"
        if set(pat) != {"*"}:
            keys.setdefault(pat, node)

    for fn in fns:
        for node in ast.walk(fn):
            if isinstance(node, ast.Subscript) and \
                    isinstance(node.ctx, ast.Load):
                add(_key_pattern(node.slice, consts), node)
            elif isinstance(node, ast.Call):
                name = dotted_name(node.func)
                last = name.split(".")[-1] if name else (
                    node.func.attr if isinstance(node.func, ast.Attribute)
                    else None)
                if last in _GET_NAMES and node.args:
                    add(_key_pattern(node.args[0], consts), node)
                elif last == "startswith" and node.args:
                    add(_key_pattern(node.args[0], consts), node,
                        prefix=True)
            elif isinstance(node, ast.Compare) and len(node.ops) == 1 and \
                    isinstance(node.ops[0], (ast.In, ast.NotIn)):
                add(_key_pattern(node.left, consts), node)
    return keys


def _strip(pat: str, prefixes: list[str]) -> str:
    for pre in prefixes:
        # ``r*|_pseq`` with strip prefix ``r*|`` -> ``_pseq``; match the
        # literal tail after the last glob char of the prefix.
        tail = pre.rstrip("*")
        if "*" in pre:
            lit = pre.split("*")[-1]
            if lit and lit in pat:
                head, _, rest = pat.partition(lit)
                if fnmatch.fnmatchcase(head + lit, pre):
                    return rest
        elif pat.startswith(tail):
            return pat[len(tail):]
    return pat


def _matches(a: str, b: str) -> bool:
    return (a == b or fnmatch.fnmatchcase(a, b)
            or fnmatch.fnmatchcase(b, a))


@register
class WireSchemaRule(Rule):
    id = "DPG005"
    name = "wire-schema-symmetry"
    invariant = ("every frame key packed is unpacked somewhere (and vice "
                 "versa) so the wire vocabulary stays symmetric across "
                 "codecs")

    def check(self, module: Module, config) -> list:
        fopts = config.file_options(self.id, module.relpath)
        pack_names = set(fopts.get("pack_functions", []))
        unpack_names = set(fopts.get("unpack_functions", []))
        if not pack_names or not unpack_names:
            return []
        strip_prefixes = fopts.get("strip_prefixes", [])
        consts = _module_str_constants(module.tree)
        # Constants imported from sibling modules can't be resolved from
        # this module's AST alone; the config pins their values.
        consts.update(fopts.get("constants", {}))
        packed = _collect_packed(
            _functions_by_name(module.tree, pack_names), consts)
        unpacked = _collect_unpacked(
            _functions_by_name(module.tree, unpack_names), consts)
        packed = {_strip(k, strip_prefixes): v for k, v in packed.items()
                  if set(_strip(k, strip_prefixes)) != {"*"}
                  and _strip(k, strip_prefixes)}

        findings = []
        for key, node in sorted(packed.items()):
            if not any(_matches(key, u) for u in unpacked):
                findings.append(self.finding(
                    module, node,
                    f"wire key {key!r} is packed but never unpacked by "
                    f"{'/'.join(sorted(unpack_names))} — dead wire bytes "
                    "or a missing decoder"))
        for key, node in sorted(unpacked.items()):
            if not any(_matches(key, p) for p in packed):
                findings.append(self.finding(
                    module, node,
                    f"wire key {key!r} is unpacked but never packed by "
                    f"{'/'.join(sorted(pack_names))} — silent None/"
                    "KeyError path against a current peer"))
        return findings
