"""DPG003: no implicit device->host transfers in hot-path loop bodies.

The solver and serving drivers are engineered around ONE stacked readback
per eval (``_make_central_metrics`` / the batched metrics program) — on a
tunneled TPU every extra materialization is a full network round-trip in
the innermost loop.  This pass flags the expressions that implicitly
force a transfer inside ``for``/``while`` bodies of the configured hot
functions:

* ``np.asarray(...)`` / ``np.array(...)`` on anything,
* ``.block_until_ready()`` and ``.item()``,
* ``float(...)`` / ``int(...)`` / ``bool(...)`` applied directly to a
  call result or a subscript/attribute of one (values already fetched to
  host — plain names — don't transfer again and are not flagged),
* any call whose (dotted-tail) name appears in the configured
  ``sync_calls`` list — the project's OWN fetch seams (``rbcd._host_fetch``,
  the one function every sanctioned driver readback routes through since
  the verdict-word loop), so wrapping a transfer in the seam helper does
  not hide it from the rule.

The sanctioned readback seams (the per-eval stacked fetch, the verdict-
word/lazy-history fetches) carry reviewed ``# dpgolint: disable=DPG003``
suppressions; anything else is a hot-loop regression.
"""

from __future__ import annotations

import ast

from ..core import Module, Rule, dotted_name, register, \
    walk_skipping_functions

_NUMPY_FETCHERS = {"asarray", "array", "ascontiguousarray", "copy"}
_CAST_BUILTINS = {"float", "int", "bool"}


def _numpy_aliases(tree: ast.AST) -> set[str]:
    aliases = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "numpy":
                    aliases.add(a.asname or "numpy")
    return aliases


def _forces_fetch(arg: ast.AST) -> bool:
    """Casts transfer only when applied to fresh device values: a call
    result, or a subscript/attribute peeled off one."""
    if isinstance(arg, ast.Call):
        return True
    if isinstance(arg, (ast.Subscript, ast.Attribute)):
        return _forces_fetch(arg.value)
    return False


@register
class HostSyncRule(Rule):
    id = "DPG003"
    name = "host-sync-hazard"
    invariant = ("hot-path loop bodies perform no implicit device->host "
                 "transfers outside the sanctioned readback seams")

    def check(self, module: Module, config) -> list:
        fopts = config.file_options(self.id, module.relpath)
        ropts = config.rule_options(self.id)
        hot = set(fopts.get("hot_functions",
                            ropts.get("hot_functions", [])))
        if not hot:
            return []
        sync_calls = set(fopts.get("sync_calls",
                                   ropts.get("sync_calls", [])))
        np_names = _numpy_aliases(module.tree)
        findings = []
        for node in ast.walk(module.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and node.name in hot:
                findings.extend(self._check_fn(module, node, np_names,
                                               sync_calls))
        return findings

    def _check_fn(self, module: Module, fn: ast.AST, np_names: set[str],
                  sync_calls: set[str]) -> list:
        out = []
        seen: set[int] = set()
        for loop in walk_skipping_functions(fn):
            if not isinstance(loop, (ast.For, ast.While)):
                continue
            for node in walk_skipping_functions(loop):
                if not isinstance(node, ast.Call) or id(node) in seen:
                    continue
                seen.add(id(node))
                hit = self._classify(node, np_names, sync_calls)
                if hit:
                    out.append(self.finding(
                        module, node,
                        f"{hit} inside the {fn.name!r} hot loop — implicit "
                        "device->host transfer; batch it into the "
                        "per-eval stacked readback or add a reviewed "
                        "suppression at a sanctioned seam"))
        return out

    def _classify(self, call: ast.Call, np_names: set[str],
                  sync_calls: set[str] = frozenset()) -> str | None:
        name = dotted_name(call.func)
        if name is not None:
            parts = name.split(".")
            if name in sync_calls or parts[-1] in sync_calls:
                return f"{name}(...) [configured sync seam]"
            if len(parts) >= 2 and parts[0] in np_names \
                    and parts[-1] in _NUMPY_FETCHERS:
                return f"{name}(...)"
            if name in _CAST_BUILTINS and call.args \
                    and _forces_fetch(call.args[0]):
                return f"{name}() on a call result"
            if parts[-1] in ("item", "block_until_ready") and len(parts) > 1:
                return f".{parts[-1]}()"
        elif isinstance(call.func, ast.Attribute) \
                and call.func.attr in ("item", "block_until_ready"):
            return f".{call.func.attr}()"
        return None
