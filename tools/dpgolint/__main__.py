"""CLI: ``python -m tools.dpgolint [paths...]``.

Exit codes: 0 clean (or every finding accepted by the baseline), 1 new
findings, 2 usage/configuration error.  ``--format json`` emits one
machine-readable object (the CI ``static-analysis`` job's interface);
the default text format is ``path:line:col: RULE message`` per finding.

The baseline (``tools/dpgolint/baseline.json``, committed EMPTY) exists
so the gate can be landed together with any accepted debt explicit and
reviewable; ``--write-baseline`` regenerates it from the current tree.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from . import rules  # noqa: F401  (register passes)
from .config import project_config
from .core import (REGISTRY, load_baseline, render_text, run_lint,
                   split_by_baseline)

DEFAULT_BASELINE = os.path.join(os.path.dirname(__file__), "baseline.json")


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.dpgolint",
        description="project-invariant static analysis for dpgo_tpu")
    ap.add_argument("paths", nargs="*", default=["dpgo_tpu", "tools"],
                    help="files/directories to lint "
                         "(default: dpgo_tpu tools)")
    ap.add_argument("--format", choices=("text", "json"), default="text")
    ap.add_argument("--rules", metavar="IDS",
                    help="comma-separated rule ids to run (default: all)")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE,
                    help="accepted-findings file (default: the committed "
                         "empty baseline)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore the baseline; any finding fails")
    ap.add_argument("--write-baseline", action="store_true",
                    help="accept the current findings into --baseline")
    ap.add_argument("--list-rules", action="store_true")
    args = ap.parse_args(argv)

    if args.list_rules:
        for rid in sorted(REGISTRY):
            r = REGISTRY[rid]
            print(f"{rid} {r.name}: {r.invariant}")
        return 0

    rule_ids = None
    if args.rules:
        rule_ids = [r.strip().upper() for r in args.rules.split(",")]
        unknown = [r for r in rule_ids if r not in REGISTRY]
        if unknown:
            print(f"unknown rule(s): {', '.join(unknown)} "
                  f"(have: {', '.join(sorted(REGISTRY))})", file=sys.stderr)
            return 2
    missing = [p for p in args.paths if not os.path.exists(p)]
    if missing:
        print(f"no such path(s): {', '.join(missing)}", file=sys.stderr)
        return 2

    findings = run_lint(args.paths, project_config(), rules=rule_ids)

    if args.write_baseline:
        with open(args.baseline, "w", encoding="utf-8") as fh:
            json.dump([f.as_dict() for f in findings], fh, indent=1)
            fh.write("\n")
        print(f"wrote {len(findings)} finding(s) to {args.baseline}")
        return 0

    baseline = [] if args.no_baseline else load_baseline(args.baseline)
    new, known, stale = split_by_baseline(findings, baseline)

    if args.format == "json":
        print(json.dumps({
            "findings": [f.as_dict() for f in new],
            "baselined": [f.as_dict() for f in known],
            "stale_baseline": stale,
            "count": len(new),
        }, indent=1))
    else:
        if new:
            print(render_text(new))
        if known:
            print(f"({len(known)} baselined finding(s) suppressed)",
                  file=sys.stderr)
        if stale:
            print(f"({len(stale)} stale baseline entr(ies) — clean them "
                  "up)", file=sys.stderr)
        if not new:
            print(f"dpgolint: clean ({len(REGISTRY)} rules, "
                  f"{', '.join(args.paths)})")
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
