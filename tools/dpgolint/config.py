"""dpgolint configuration: which rules look where, and what they trust.

``Config`` carries two maps:

* ``files`` — rule id -> list of path globs (lint-root-relative, forward
  slashes) the rule runs on.  ``None``/missing = every file.  This is how
  each invariant stays scoped to the layer that owes it (DPG001 to the
  jit hot paths, DPG005 to the wire modules) instead of pattern-matching
  the whole tree.
* ``options`` — rule id -> rule-specific settings dict.  Per-file
  settings nest one level deeper keyed by path glob (see
  ``Config.file_options``).

``project_config()`` is the checked-in project policy — the single place
the sanctioned constructor seams, hot-path function lists, and codec
pairs are declared.  Tests build ad-hoc ``Config``\\ s pointing rules at
fixture files instead.
"""

from __future__ import annotations

import dataclasses

from .core import glob_match


@dataclasses.dataclass
class Config:
    files: dict = dataclasses.field(default_factory=dict)
    options: dict = dataclasses.field(default_factory=dict)

    def applies(self, rule_id: str, relpath: str) -> bool:
        globs = self.files.get(rule_id)
        if globs is None:
            return True
        return glob_match(relpath, globs)

    def rule_options(self, rule_id: str) -> dict:
        return self.options.get(rule_id, {})

    def file_options(self, rule_id: str, relpath: str) -> dict:
        """The per-file settings block for ``relpath``: the value under the
        first glob key in ``options[rule_id]["per_file"]`` that matches."""
        per_file = self.rule_options(rule_id).get("per_file", {})
        for pat, opts in per_file.items():
            if glob_match(relpath, [pat]):
                return opts
        return {}


def project_config() -> Config:
    """The dpgo_tpu project policy (see docs/ARCHITECTURE.md, "Static
    analysis & invariants")."""
    return Config(
        files={
            # DPG001: functions reachable from jax.jit/vmap/fused-segment
            # entry points must be pure — these are the modules that build
            # the compiled solver/serving programs.
            "DPG001": [
                "dpgo_tpu/models/rbcd.py",
                "dpgo_tpu/models/incremental.py",
                "dpgo_tpu/serve/runner.py",
                "dpgo_tpu/parallel/sharded.py",
                "dpgo_tpu/parallel/resilience.py",
            ],
            # DPG002: obs-owned constructions anywhere in the package must
            # sit behind the telemetry fence; the obs internals that ARE
            # the fence (run/trace/health/recorder construct their own
            # objects behind documented contracts + boom tests) are the
            # sanctioned seams.  The third-level glob keeps sub-subpackages
            # (serve/fleet) explicitly in scope.
            "DPG002": ["dpgo_tpu/*", "dpgo_tpu/*/*", "dpgo_tpu/*/*/*"],
            # DPG003: host-sync hazards in the solver/serving hot loops.
            "DPG003": [
                "dpgo_tpu/models/rbcd.py",
                "dpgo_tpu/models/incremental.py",
                "dpgo_tpu/models/certify.py",
                "dpgo_tpu/serve/runner.py",
                "dpgo_tpu/parallel/sharded.py",
                "dpgo_tpu/parallel/certify.py",
                "dpgo_tpu/parallel/resilience.py",
                "dpgo_tpu/parallel/multihost.py",
                "dpgo_tpu/serve/fleet/procs.py",
            ],
            # DPG004 is annotation-driven (# guarded-by) — run everywhere;
            # files without annotations produce nothing.
            "DPG004": None,
            # DPG005: the wire vocabulary modules.
            "DPG005": [
                "dpgo_tpu/comms/protocol.py",
                "dpgo_tpu/comms/reliable.py",
                "dpgo_tpu/comms/bus.py",
            ],
        },
        options={
            "DPG001": {
                # Fused-segment entry points that are jitted indirectly
                # (module-level jax.jit(...) wrappers already detect most).
                "extra_entries": ["_rbcd_segment", "_rbcd_round",
                                  "_rbcd_rounds"],
            },
            "DPG002": {
                "constructors": ["TelemetryRun", "HealthMonitor",
                                 "FlightRecorder", "MetricsSidecar",
                                 "ProfiledExecutable", "ProfilerWindow",
                                 "Span", "DeviceTraceWindow",
                                 "PerfLedger", "ResourceSampler",
                                 "FleetSidecar"],
                # Obs-owned modules where construction IS the sanctioned
                # implementation of the fence (each carries its own boom
                # test): start_run/run_scope, span()/start_span(),
                # monitor_for, FlightRecorder.attach + the replay CLI.
                # devprof constructs its own trace windows behind
                # ``get_run()`` checks; ledger.py is offline tooling
                # whose PerfLedger only ever exists via load_ledger.
                "allowed_files": [
                    "dpgo_tpu/obs/run.py",
                    "dpgo_tpu/obs/trace.py",
                    "dpgo_tpu/obs/health.py",
                    "dpgo_tpu/obs/recorder.py",
                    "dpgo_tpu/obs/devprof.py",
                    "dpgo_tpu/obs/ledger.py",
                    "dpgo_tpu/obs/fleetobs.py",
                ],
            },
            "DPG003": {
                "per_file": {
                    "dpgo_tpu/models/rbcd.py": {
                        # _run_verdict_loop is the device-resident driver
                        # (ISSUE 9): its ONLY sanctioned in-loop fetches
                        # are the verdict word, the lazy history, and the
                        # terminal bookkeeping — each carries a reviewed
                        # suppression; _host_fetch is the seam they all
                        # route through, and any new call to it inside a
                        # hot loop is flagged.
                        "hot_functions": ["run_rbcd", "dispatch_prepared",
                                          "solve_rbcd",
                                          "_run_verdict_loop"],
                        "sync_calls": ["_host_fetch"],
                    },
                    "dpgo_tpu/serve/runner.py": {
                        "hot_functions": ["run_bucket"],
                        "sync_calls": ["_host_fetch"],
                    },
                    # The live-session layer (ISSUE 10): delta application
                    # and the warm-restart dispatch are host-side by
                    # design, but they sit on the serving worker's request
                    # path — a device sync creeping into their loops would
                    # stall every batch behind a stream.
                    "dpgo_tpu/models/incremental.py": {
                        "hot_functions": ["apply_edges", "_try_delta",
                                          "warm_dispatch", "_adapt_state"],
                    },
                    # The sharded driver loop (ISSUE 11): the sharded
                    # GN-CG tail's outer loop reads one gate scalar and
                    # one stats vector per outer step through the same
                    # sanctioned seam as the verdict loop; anything else
                    # inside it (or inside a future solve_rbcd_sharded
                    # loop) is a hot-loop regression on the mesh path.
                    "dpgo_tpu/parallel/sharded.py": {
                        "hot_functions": ["gn_tail_sharded",
                                          "solve_rbcd_sharded"],
                        "sync_calls": ["_host_fetch"],
                    },
                    # The resilience layer (ISSUE 14): the checkpoint
                    # gather is the ONE sanctioned device->host transfer
                    # of the whole subsystem — it runs only at a verdict
                    # boundary the driver already paid a word-fetch for,
                    # through resilience.py's own _host_fetch seam (so
                    # the driver's sync-rate contract is untouched), and
                    # carries a reviewed suppression.  Any other fetch in
                    # the checkpoint/boundary loop is a new steady-state
                    # sync and is flagged.
                    "dpgo_tpu/parallel/resilience.py": {
                        "hot_functions": ["checkpoint_arrays",
                                          "boundary_cb"],
                        "sync_calls": ["_host_fetch"],
                    },
                    # The certificate layer (ISSUE 15): the device
                    # certificate rides the solve's fused terminal
                    # epilogue, so the ONE sanctioned transfer is that
                    # terminal ``_host_fetch`` — the staircase loops
                    # (which re-certify per rank) must route every
                    # readback through it rather than fetching scalars
                    # ad hoc between escapes.
                    "dpgo_tpu/models/certify.py": {
                        "hot_functions": ["solve_staircase",
                                          "device_certificate_payload",
                                          "decide_device_certificate"],
                        "sync_calls": ["_host_fetch"],
                    },
                    "dpgo_tpu/parallel/certify.py": {
                        "hot_functions": ["solve_staircase_sharded",
                                          "certify_sharded",
                                          "make_sharded_certificate"],
                        "sync_calls": ["_host_fetch"],
                    },
                    # The multi-host lockstep (ISSUE 17): verdict_sync
                    # rides the ONE word the driver already fetched — it
                    # trades host bytes over the coordination service and
                    # must never touch the device; a fetch creeping into
                    # its publish/cross-check loop (or into the per-round
                    # boundary_cb it hangs off) would multiply the
                    # cross-process sync rate past 100/K.
                    "dpgo_tpu/parallel/multihost.py": {
                        "hot_functions": ["verdict_sync", "boundary_cb",
                                          "run_worker"],
                        "sync_calls": ["_host_fetch"],
                    },
                    # The out-of-process fleet (ISSUE 17): the pump and
                    # heartbeat threads sit on the parent's request path —
                    # host-only by design (the device lives in the child),
                    # so any numpy materialization or ad-hoc ``_rpc`` in
                    # their loops is a new blocking stall behind a live
                    # replica socket.
                    "dpgo_tpu/serve/fleet/procs.py": {
                        "hot_functions": ["_pump", "_heartbeat_loop",
                                          "submit"],
                        "sync_calls": ["_rpc"],
                    },
                },
            },
            "DPG005": {
                "per_file": {
                    "dpgo_tpu/comms/protocol.py": {
                        "pack_functions": ["pack_pose_dict",
                                           "pack_pose_arrays",
                                           "pack_trace_entries",
                                           "pack_measurements",
                                           "attach_clock"],
                        "unpack_functions": ["unpack_pose_dict",
                                             "unpack_pose_arrays",
                                             "unpack_trace_entries",
                                             "unpack_measurements",
                                             "pop_clock"],
                    },
                    "dpgo_tpu/comms/reliable.py": {
                        "pack_functions": ["send"],
                        "unpack_functions": ["_recv"],
                        # Imported from protocol.py; pinned so the clock
                        # stamp participates in the symmetry check.
                        "constants": {"CLOCK_KEY": "_ts"},
                    },
                    "dpgo_tpu/comms/bus.py": {
                        "pack_functions": ["pack_agent_frame", "hello",
                                           "round"],
                        "unpack_functions": ["apply_peer_frame",
                                             "_apply_peer_frame",
                                             "collect", "accept_robots",
                                             "_gather_one"],
                        # The hub namespaces rebroadcast keys r{id}|...;
                        # receivers split the prefix off before parsing.
                        "strip_prefixes": ["r*|"],
                    },
                },
            },
        },
    )
