"""dpgolint core: the rule framework.

The project's load-bearing invariants — the zero-overhead telemetry
fence, pure jit-reachable code, no host syncs in hot loops, lock-guarded
shared state, symmetric wire codecs — are each one rule here.  A rule is
a class with an ``id`` (``DPGnnn``), registered in ``REGISTRY``, whose
``check(module, config)`` returns ``Finding``\\ s.  The framework owns
everything rule-independent: file walking, AST parsing with parent
links, inline ``# dpgolint: disable=RULE`` suppressions, the committed
baseline, and text/JSON rendering (``python -m tools.dpgolint``).

Suppressions
------------

``# dpgolint: disable=DPG003 -- <reason>`` on (or immediately above) the
offending line suppresses that rule there; a reason after ``--`` is
convention, not syntax.  ``# dpgolint: disable-file=DPG004`` anywhere in
a file suppresses the rule for the whole file.  Suppressions are the
reviewed escape hatch for sanctioned sites (e.g. the two readback seams
DPG003 allowlists); new code should satisfy the rule instead.
"""

from __future__ import annotations

import ast
import dataclasses
import fnmatch
import json
import os
import re

_SUPPRESS_RE = re.compile(
    r"#.*?\bdpgolint:\s*disable(?P<file>-file)?\s*=\s*"
    r"(?P<rules>[A-Za-z0-9_]+(?:\s*,\s*[A-Za-z0-9_]+)*)")


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation at a source location."""

    rule: str
    path: str       # lint-root-relative, forward slashes
    line: int
    col: int
    message: str

    @property
    def location(self) -> str:
        return f"{self.path}:{self.line}"

    def as_dict(self) -> dict:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "col": self.col, "message": self.message}

    @property
    def baseline_key(self) -> str:
        """Line numbers churn on unrelated edits; the baseline keys on
        (rule, file, message) so accepted debt survives reflows."""
        return f"{self.rule}|{self.path}|{self.message}"


class Module:
    """One parsed source file: AST with parent links, source lines,
    suppression table."""

    def __init__(self, path: str, relpath: str, source: str):
        self.path = path
        self.relpath = relpath.replace(os.sep, "/")
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        for parent in ast.walk(self.tree):
            for child in ast.iter_child_nodes(parent):
                child._dpgolint_parent = parent  # type: ignore[attr-defined]
        self._line_suppress: dict[int, set[str]] = {}
        self._file_suppress: set[str] = set()
        for lineno, text in enumerate(self.lines, start=1):
            m = _SUPPRESS_RE.search(text)
            if not m:
                continue
            rules = {r.strip().upper() for r in m.group("rules").split(",")}
            if m.group("file"):
                self._file_suppress |= rules
                continue
            self._line_suppress.setdefault(lineno, set()).update(rules)
            # A comment-only line covers the statement below it; a
            # trailing comment covers only its own line.
            if text.lstrip().startswith("#"):
                self._line_suppress.setdefault(lineno + 1,
                                               set()).update(rules)

    # -- tree helpers -------------------------------------------------------

    def parent(self, node: ast.AST) -> ast.AST | None:
        return getattr(node, "_dpgolint_parent", None)

    def ancestors(self, node: ast.AST):
        cur = self.parent(node)
        while cur is not None:
            yield cur
            cur = self.parent(cur)

    def enclosing_function(self, node: ast.AST):
        for anc in self.ancestors(node):
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda)):
                return anc
        return None

    def enclosing_class(self, node: ast.AST) -> ast.ClassDef | None:
        for anc in self.ancestors(node):
            if isinstance(anc, ast.ClassDef):
                return anc
        return None

    # -- suppressions -------------------------------------------------------

    def is_suppressed(self, rule: str, line: int) -> bool:
        if rule in self._file_suppress:
            return True
        return rule in self._line_suppress.get(line, ())


def dotted_name(expr: ast.AST) -> str | None:
    """``a.b.c`` for Name/Attribute chains, else None (calls, subscripts
    and anything dynamic break the chain)."""
    if isinstance(expr, ast.Name):
        return expr.id
    if isinstance(expr, ast.Attribute):
        base = dotted_name(expr.value)
        return None if base is None else f"{base}.{expr.attr}"
    return None


def walk_skipping_functions(node: ast.AST, *, skip_root_check: bool = True):
    """Yield ``node``'s descendants without descending into nested
    function/lambda bodies — the unit rules reason about is ONE function's
    own statements (nested defs are separate call-graph nodes)."""
    stack = list(ast.iter_child_nodes(node))
    while stack:
        cur = stack.pop()
        yield cur
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(cur))


class Rule:
    """Base class; subclasses set ``id``/``name``/``invariant`` and
    implement ``check``."""

    id = "DPG000"
    name = "unnamed"
    invariant = ""

    def check(self, module: Module, config) -> list[Finding]:
        raise NotImplementedError

    def finding(self, module: Module, node: ast.AST | None, message: str,
                line: int | None = None) -> Finding:
        return Finding(
            rule=self.id, path=module.relpath,
            line=line if line is not None else getattr(node, "lineno", 0),
            col=getattr(node, "col_offset", 0), message=message)


REGISTRY: dict[str, Rule] = {}


def register(rule_cls):
    """Class decorator adding a rule to the global registry."""
    REGISTRY[rule_cls.id] = rule_cls()
    return rule_cls


# ---------------------------------------------------------------------------
# Running
# ---------------------------------------------------------------------------

def _relpath(abspath: str, base: str) -> str:
    """Repo-relative when under the working directory (what the config
    globs are written against — ``dpgo_tpu/...``), else relative to the
    lint root's parent (fixture trees in tmp dirs)."""
    rel = os.path.relpath(abspath, os.getcwd())
    if rel.startswith(".."):
        rel = os.path.relpath(abspath, base)
    return rel


def _iter_py_files(paths: list[str]) -> list[tuple[str, str]]:
    out = []
    for root in paths:
        root = os.path.normpath(root)
        base = os.path.dirname(os.path.abspath(root))
        if os.path.isfile(root):
            p = os.path.abspath(root)
            out.append((p, _relpath(p, base)))
            continue
        for dirpath, dirnames, filenames in os.walk(root):
            dirnames[:] = sorted(d for d in dirnames
                                 if d != "__pycache__"
                                 and not d.startswith("."))
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    p = os.path.abspath(os.path.join(dirpath, fn))
                    out.append((p, _relpath(p, base)))
    return out


def run_lint(paths: list[str], config, rules: list[str] | None = None
             ) -> list[Finding]:
    """Lint every .py file under ``paths`` with the registered rules
    (optionally restricted to ``rules`` ids); returns suppression-filtered
    findings sorted by location."""
    active = {rid: rule for rid, rule in REGISTRY.items()
              if rules is None or rid in rules}
    findings: list[Finding] = []
    for path, relpath in _iter_py_files(paths):
        with open(path, encoding="utf-8") as fh:
            source = fh.read()
        try:
            module = Module(path, relpath, source)
        except SyntaxError as e:
            findings.append(Finding(
                rule="DPG000", path=relpath.replace(os.sep, "/"),
                line=e.lineno or 0, col=e.offset or 0,
                message=f"syntax error: {e.msg}"))
            continue
        for rid in sorted(active):
            rule = active[rid]
            if not config.applies(rid, module.relpath):
                continue
            for f in rule.check(module, config):
                if not module.is_suppressed(f.rule, f.line):
                    findings.append(f)
    return sorted(findings, key=lambda f: (f.path, f.line, f.rule, f.message))


# ---------------------------------------------------------------------------
# Baseline
# ---------------------------------------------------------------------------

def load_baseline(path: str) -> list[dict]:
    if not os.path.exists(path):
        return []
    with open(path, encoding="utf-8") as fh:
        data = json.load(fh)
    if not isinstance(data, list):
        raise ValueError(f"baseline {path}: expected a JSON list")
    return data


def split_by_baseline(findings: list[Finding], baseline: list[dict]
                      ) -> tuple[list[Finding], list[Finding], list[dict]]:
    """(new, known, stale): findings not in the baseline, findings the
    baseline accepts, and baseline entries nothing matched (candidates for
    deletion)."""
    keys = {f"{b['rule']}|{b['path']}|{b['message']}" for b in baseline}
    new = [f for f in findings if f.baseline_key not in keys]
    known = [f for f in findings if f.baseline_key in keys]
    seen = {f.baseline_key for f in findings}
    stale = [b for b in baseline
             if f"{b['rule']}|{b['path']}|{b['message']}" not in seen]
    return new, known, stale


def render_text(findings: list[Finding]) -> str:
    lines = []
    for f in findings:
        lines.append(f"{f.location}:{f.col}: {f.rule} {f.message}")
    return "\n".join(lines)


def glob_match(relpath: str, patterns) -> bool:
    return any(fnmatch.fnmatchcase(relpath, pat) for pat in patterns)
