"""Benchmark #2: time to certified 1e-6 relative suboptimality.

The north-star target (BASELINE.md) is stated two ways: RBCD rounds/sec
(``bench.py``, the driver metric) and **time-to-1e-6 relative
suboptimality at matching certified gap** — this script measures the
second.  Default configuration is the north-star config #2 (sphere2500,
8 agents, r=5); env-overridable: ``BENCH_DATASET`` (any .g2o path),
``BENCH_ROBOTS``, ``BENCH_RANK``, ``BENCH_SCHEDULE`` (jacobi | colored |
greedy | async), ``BENCH_CPU=1`` runs the f64 CPU comparison arm of the
SAME pipeline.  Protocol:

1. Establish the certified optimum f* once: a centralized float64 CPU
   solve driven to gradnorm <= 1e-9, certified by the dual-certificate
   eigensolve (``models.certify``) — the relaxation is tight at r=5 on
   sphere2500, so f* is the global PGO optimum, not just a local anchor.
2. Run the distributed solver (fused rounds) and time how long until the
   centralized cost of the assembled iterate reaches
   ``f <= f* * (1 + 1e-6)``, checking every ``EVAL_EVERY`` rounds.
   Timing by device-to-host readback (see bench.py on why
   block_until_ready cannot be trusted on the tunneled platform).

Prints one JSON line:
  {"metric": "time_to_<gap>_subopt_<dataset>_<A>agents_r<r>"
        (gap spelled "1e-6"-style — the historical key for default runs),
   "value": <s>, "unit": "s", "rounds": N, "f_opt": ..., "certified": true}
"""

from __future__ import annotations

import json
import os
import sys
import time
from typing import NamedTuple

import numpy as np

# Dataset / partition are env-overridable so the same certified-gap
# protocol runs on any benchmark graph (default: the north-star config #2).
DATASET = os.environ.get("BENCH_DATASET",
                         "/root/reference/data/sphere2500.g2o")
NUM_ROBOTS = int(os.environ.get("BENCH_ROBOTS", "8"))
RANK = int(os.environ.get("BENCH_RANK", "5"))
# Schedule: any Schedule enum value; "jacobi" is the north-star config's
# default, "colored" the stable choice for graphs where simultaneous
# adjacent-block updates oscillate (the ais2klinik/parking-garage failure
# mode, BASELINE.md).
SCHEDULE = os.environ.get("BENCH_SCHEDULE", "jacobi")
_DSET = os.path.splitext(os.path.basename(DATASET))[0]
REL_GAP = float(os.environ.get("BENCH_REL_GAP", "1e-6"))
# Each eval is a device->host readback (~50-90 ms on the tunnel), so the
# cadence is a real cost: 50 keeps 2-3 evals on the path to the handoff.
EVAL_EVERY = int(os.environ.get("BENCH_EVAL_EVERY", "50"))
MAX_ROUNDS = int(os.environ.get("BENCH_MAX_ROUNDS", "4000"))
# Nesterov acceleration for the descent phase (both backends, honest A/B).
# restart_interval=100: measured on sphere2500 (experiments/accel_rounds.py)
# — rounds to 1e-5 drop 230 -> 135 vs plain, and longer intervals than the
# reference's 30 are strictly better on this problem (30 is a wash).
ACCEL = os.environ.get("BENCH_ACCEL", "1") == "1"
RESTART_INTERVAL = int(os.environ.get("BENCH_RESTART", "100"))
# Refine: accelerated cycles (adaptive restart) — one long cycle replaces
# several recenter round-trips.  0 = adaptive: cycle length proportional
# to the decades of gap to cover (~73 rounds/decade measured), see main().
REFINE_ROUNDS = int(os.environ.get("BENCH_REFINE_ROUNDS", "0"))
# First descent segment before the first cost eval (classic path) /
# before the fused recenter (fused path).  Round-5 sweep on the fused
# pipeline: 125 -> 0.338 s, 110 -> 0.307-0.308 s, 90 -> 0.307 s with the
# refine phase absorbing the shorter descent at no extra cycle cost; 110
# keeps a margin above the oracle's 0.3x stopping band (gap 1.9e-7).
FIRST_SEGMENT = int(os.environ.get("BENCH_FIRST_SEGMENT", "110"))
# Kernel selection-matmul mode ("f32", "bf16", "bf16x3" —
# config.SolverParams.pallas_sel_mode).  bf16x3 covers the full f32
# mantissa at half the HIGHEST-emulation MXU passes (f32-grade: per-round
# drift ~3e-5, reduction-order scale); it applies to the descent AND the
# refine kernel (measured identical refine result on sphere2500).  The
# 2-pass "bf16" mode is never used by refinement (models/refine.py).
SEL_MODE = os.environ.get("BENCH_SEL_MODE", "bf16x3")
# Descent-phase tCG budget (the refine phase shares it).  6 measured best
# on the north star: rounds are ~1.5x faster than the tol-forced 10 and
# the handoff still lands at ~2e-5 in one 125-round segment (sweep:
# 10 -> 0.44s, 8 -> 0.43s, 6 -> 0.42s total).
INNER_ITERS = int(os.environ.get("BENCH_INNER_ITERS", "6"))
# Fused single-readback pipeline (VERDICT r4 item 1): descent -> on-device
# df32 recenter -> oracle-terminated refine, ONE readback + host f64
# verify at the end (models.refine_fused).  Default on the accelerator;
# BENCH_FUSED=0 restores the round-4 host-recenter pipeline, and any
# fused run whose host verify misses the target falls back to it anyway.
FUSED = os.environ.get("BENCH_FUSED", "1") == "1"
# 1 cycle suffices on the north star (one recenter covers the ~2 decades
# from the descent handoff; measured gap 1.7-2.9e-7 across the sweep) and
# a second cycle costs a full extra recenter (0.384 vs 0.338 s); problems
# that DO need more cycles fall through to the host-recenter fallback.
FUSED_CYCLES = int(os.environ.get("BENCH_FUSED_CYCLES", "1"))
FUSED_MAX_ROUNDS = int(os.environ.get("BENCH_FUSED_MAX_ROUNDS", "192"))
FUSED_CHECK_EVERY = int(os.environ.get("BENCH_FUSED_CHECK", "8"))
# Refine contraction model: rounds per decade of gap for the adaptive
# cycle length.  Measured 47-73 across hours/budgets on sphere2500; 60
# with the 0.3x target margin keeps ~2-3x landing margin while not
# overshooting two decades past the target (the per-cycle f64 verify +
# extra-cycle fallback still catches slow-contracting problems).
DECADE_ROUNDS = int(os.environ.get("BENCH_DECADE_ROUNDS", "65"))


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def _finite_or_none(x) -> float | None:
    """JSON-safe gap value: json.dumps would emit the non-standard token
    ``Infinity`` for a diverged-cycle history entry, breaking any strict
    JSON consumer of the benchmark line."""
    import math
    x = float(x)
    return x if math.isfinite(x) else None


def certified_optimum():
    """f* from a float64 centralized solve + dual certificate (cached)."""
    cache = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         f".bench_fopt_{_DSET}_r{RANK}.json")
    if os.path.exists(cache):
        with open(cache) as f:
            d = json.load(f)
        log(f"  cached f* = {d['f_opt']:.9f} (certified={d['certified']})")
        return d["f_opt"], d["certified"]

    import subprocess
    out = subprocess.run(
        [sys.executable, os.path.abspath(__file__)],
        env=dict(os.environ, BENCH_MODE="fopt"),
        capture_output=True, text=True, timeout=3600)
    sys.stderr.write(out.stderr)
    if out.returncode != 0:
        raise RuntimeError(f"f* solve failed:\n{out.stderr[-2000:]}")
    d = json.loads(out.stdout.strip().splitlines()[-1])
    with open(cache, "w") as f:
        json.dump(d, f)
    return d["f_opt"], d["certified"]


def fopt_main():
    """Subprocess: centralized f64 CPU solve + certificate (the TPU-tunnel
    process cannot enable x64, see bench.py)."""
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)
    import jax.numpy as jnp
    from dpgo_tpu.models import certify
    from dpgo_tpu.models.local_pgo import solve_local
    from dpgo_tpu.types import edge_set_from_measurements
    from dpgo_tpu.utils.g2o import read_g2o

    meas = read_g2o(DATASET)
    res = solve_local(meas, rank=RANK, grad_norm_tol=1e-9, max_iters=1000,
                      dtype=jnp.float64)
    edges = edge_set_from_measurements(meas, dtype=jnp.float64)
    cert = certify.certify_solution(res.X, edges)
    log(f"  f* = {float(res.cost):.9f}, gradnorm {float(res.grad_norm):.2e}, "
        f"lambda_min {cert.lambda_min:.3e}, certified={cert.certified}")
    print(json.dumps({"f_opt": float(res.cost),
                      "certified": bool(cert.certified)}))


class BenchProblem(NamedTuple):
    """Everything the descent / polish arms need, by name (the positional
    tuple outgrew itself once the host-eval path needed ``gather_of`` and
    ``part``)."""

    rbcd: object      # the models.rbcd module
    graph: object
    meta: object
    params: object
    state0: object    # None when init != "chordal"
    cost_of: object   # jitted on-device scalar cost
    edges_g: object
    n_total: int
    gather_of: object  # jitted [A, n_max, ...] -> global [N, ...]
    part: object


def _build_problem(dtype, init: str = "chordal") -> BenchProblem:
    """Shared benchmark-problem builder (main / polish subprocess): one
    definition so the polish measures exactly the problem the accelerator
    descent ran."""
    import jax
    import jax.numpy as jnp
    from dpgo_tpu.config import AgentParams, Schedule, SolverParams
    from dpgo_tpu.models import rbcd
    from dpgo_tpu.ops import quadratic
    from dpgo_tpu.types import edge_set_from_measurements
    from dpgo_tpu.utils.g2o import read_g2o
    from dpgo_tpu.utils.partition import partition_contiguous

    meas = read_g2o(DATASET)
    params = AgentParams(
        d=meas.d, r=RANK, num_robots=NUM_ROBOTS, rel_change_tol=0.0,
        acceleration=ACCEL, restart_interval=RESTART_INTERVAL,
        schedule=Schedule(SCHEDULE),
        # Drive the local solves tight: the reference's per-step budget
        # (tol 1e-2) caps achievable global suboptimality far above 1e-6.
        solver=SolverParams(grad_norm_tol=1e-9, max_inner_iters=INNER_ITERS,
                            pallas_sel_mode=SEL_MODE))
    part = partition_contiguous(meas, NUM_ROBOTS)
    graph, meta = rbcd.build_graph(part, RANK, dtype, sel_mode=SEL_MODE)
    state0 = None
    if init == "chordal":
        X0 = rbcd.centralized_chordal_init(part, meta, graph, dtype)
        state0 = rbcd.init_state(graph, meta, X0, params=params)
    edges_g = edge_set_from_measurements(part.meas_global, dtype=dtype)
    n_total = part.meas_global.num_poses

    @jax.jit
    def cost_of(s):
        return quadratic.cost(rbcd.gather_to_global(s.X, graph, n_total),
                              edges_g)

    @jax.jit
    def gather_of(s):
        return rbcd.gather_to_global(s.X, graph, n_total)

    return BenchProblem(rbcd, graph, meta, params, state0, cost_of,
                        edges_g, n_total, gather_of, part)


def advance(rbcd, graph, meta, params, state, it, k):
    """Run ``k`` rounds from round-count ``it``, honoring the Nesterov
    restart cadence — one ``rbcd_segment`` dispatch per stretch, with a
    restart round fused into the front of its following stretch (the
    run_rbcd segmentation, inlined so the bench keeps its ladder-timing
    loop; on a tunneled device each extra dispatch costs real latency)."""
    end = it + k
    while it < end:
        restart = ACCEL and (it + 1) % RESTART_INTERVAL == 0
        nxt = end
        if ACCEL:
            nxt = min(nxt, ((it + 1) // RESTART_INTERVAL + 1)
                      * RESTART_INTERVAL - 1)
        state = rbcd.rbcd_segment(state, graph, max(1, nxt - it), meta,
                                  params, first_restart=restart)
        it = nxt
    return state, it


def polish_main():
    """Subprocess: warm-started float64 CPU polish from the TPU's floored
    float32 iterate (path in BENCH_POLISH_STATE) down to the 1e-6 gap —
    the practical recipe for certified-grade output: TPU does the descent,
    a few f64 rounds do the last decimal."""
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)
    import jax.numpy as jnp

    data = np.load(os.environ["BENCH_POLISH_STATE"])
    f_opt = float(os.environ["BENCH_F_OPT"])
    target = f_opt * (1.0 + REL_GAP)

    # init="warm": skip _build_problem's chordal initialization — the
    # warm-start state comes from the accelerator's .npz.
    p = _build_problem(jnp.float64, init="warm")
    rbcd, graph, meta, params, cost_of = \
        p.rbcd, p.graph, p.meta, p.params, p.cost_of
    X0 = jnp.asarray(data["X"], jnp.float64)
    state = rbcd.init_state(graph, meta, X0, params=params)

    _ = float(cost_of(rbcd.rbcd_segment(
        state, graph, 1, meta, params, first_restart=False)))  # compile
    if ACCEL:  # the restart-first variant compiles separately (see main)
        _ = rbcd.rbcd_segment(state, graph, 1, meta, params,
                              first_restart=True)
    state = rbcd.init_state(graph, meta, X0, params=params)

    f = float(cost_of(state))  # also covers MAX_ROUNDS < 5 (loop never runs)
    t0 = time.perf_counter()
    rounds = 0
    reached = False
    while rounds < MAX_ROUNDS:
        state, rounds = advance(rbcd, graph, meta, params, state, rounds, 5)
        f = float(cost_of(state))
        if f <= target:
            reached = True
            break
    dt = time.perf_counter() - t0
    log(f"  polish: {rounds} f64 rounds, {dt:.2f}s, "
        f"rel gap {f / f_opt - 1.0:.2e}, reached={reached}")
    print(json.dumps({"polish_s": dt, "polish_rounds": rounds,
                      "rel_gap": f / f_opt - 1.0, "reached": reached}))


def main():
    if os.environ.get("BENCH_MODE") == "fopt":
        fopt_main()
        return
    if os.environ.get("BENCH_MODE") == "polish":
        polish_main()
        return

    import jax
    if os.environ.get("BENCH_CPU") == "1":
        # The f64 CPU comparison arm.  The env JAX_PLATFORMS=cpu alone is
        # not enough on this image (sitecustomize force-registers the
        # tunnel platform); pin in code like bench.py's BENCH_MODE=cpu.
        jax.config.update("jax_platforms", "cpu")
        jax.config.update("jax_enable_x64", True)
    import jax.numpy as jnp

    f_opt, certified = certified_optimum()
    target = f_opt * (1.0 + REL_GAP)

    dev = jax.devices()[0]
    log(f"benchmark device: {dev.platform} ({dev.device_kind})")
    dtype = jnp.float32 if dev.platform != "cpu" else jnp.float64

    p = _build_problem(dtype)
    (rbcd, graph, meta, params, state0, cost_of, edges_g, n_total,
     gather_of, part) = p

    # On the tunneled accelerator every device->host sync costs a fixed
    # ~90 ms round-trip, so the f32 arm evaluates cost on the HOST from a
    # single jitted full-iterate readback (f64 oracle — also the exact
    # iterate the refine phase recenters from, so the handoff pays no
    # second readback).  The CPU arm keeps the on-device scalar eval.
    host_eval = dtype == jnp.float32
    if host_eval:
        from dpgo_tpu.models import refine as refine_mod
        edges_oracle = refine_mod.host_edges_f64(part.meas_global)

    def eval_state(s):
        """Returns (f, Xg64-or-None): the benchmark's gap oracle."""
        if host_eval:
            Xg64 = np.asarray(gather_of(s), np.float64)
            return refine_mod.global_cost(Xg64, edges_oracle), Xg64
        return float(cost_of(s)), None

    # Warm-up: compile both segment variants (plain and restart-first —
    # compiling the restart variant inside the timed loop once cost
    # ~2.9 s) and the cost eval, all outside the clock.  The calls MUST
    # match advance()'s exact call pattern (explicit first_restart kwarg):
    # jit re-traces for a different bound-argument structure even when the
    # value equals the default, which once cost ~1.5 s inside the clock.
    state = rbcd.rbcd_segment(state0, graph, 1, meta, params,
                              first_restart=False)
    if ACCEL:
        _ = rbcd.rbcd_segment(state, graph, 1, meta, params,
                              first_restart=True)
    _ = eval_state(state)

    # ---- Fused single-readback pipeline (accelerator default) ----------
    # descent segments -> [on-device df32 recenter -> oracle-terminated
    # refine] x cycles -> ONE packed readback -> host f64 verify.  The
    # round-4 pipeline paid two ~90 ms tunnel round-trips (handoff eval +
    # final verify, ~47% of the wall); this path pays one.
    fused_info = None
    if FUSED and host_eval:
        # Any failure in the fused path must degrade to the proven
        # round-4 pipeline, not abort the benchmark (same contract as
        # the refine / centralized / hybrid auxiliary steps below).
        try:
            from dpgo_tpu.models import refine_fused
            from dpgo_tpu.ops import df32 as df32_mod

            gp = refine_fused.build_global_df(part.meas_global)
            fns = refine_fused.make_fused_fns(
                meta, params, n_total, max_rounds=FUSED_MAX_ROUNDS,
                check_every=FUSED_CHECK_EVERY)
            target_df = df32_mod.from_f64(
                np.float64(f_opt * (1.0 + 0.3 * REL_GAP)))
            d_shape = tuple(state.X.shape)
            # Compile the full chain outside the clock (state here is the
            # 1-round warm-up state from above).
            out_w = refine_fused.run_fused_cycles(
                fns, gather_of(state), gp, graph, target_df,
                cycles=FUSED_CYCLES)
            _ = np.asarray(fns.pack(out_w))
            log("  fused pipeline compiled")

            state = state0
            t0 = time.perf_counter()
            state, rounds = advance(rbcd, graph, meta, params, state, 0,
                                    FIRST_SEGMENT)
            out = refine_fused.run_fused_cycles(
                fns, gather_of(state), gp, graph, target_df,
                cycles=FUSED_CYCLES)
            flat = np.asarray(fns.pack(out))        # the ONE readback
            res_np = refine_fused.unpack_result_host(
                flat, n_total, RANK, meta.d + 1, d_shape)
            X64 = refine_fused.assemble_f64(res_np, graph)
            X64p = refine_mod._np_project_manifold(X64, meta.d)
            f = refine_mod.global_cost(X64p, edges_oracle)
            dt_f = time.perf_counter() - t0
            gap_f = f / f_opt - 1.0
            oracle_f = float(np.float64(res_np.f_ref_hi)
                             + np.float64(res_np.f_ref_lo)
                             + np.float64(res_np.delta))
            log(f"  fused: {dt_f:.3f}s, descent {rounds} + refine "
                f"{res_np.rounds} rounds (last cycle), verified rel gap "
                f"{gap_f:.2e} (oracle {oracle_f / f_opt - 1.0:.2e})")
            fused_info = {
                "total_s": round(dt_f, 3), "descent_rounds": rounds,
                "refine_rounds_last_cycle": int(res_np.rounds),
                "cycles": FUSED_CYCLES, "rel_gap": gap_f,
                "oracle_rel_gap": oracle_f / f_opt - 1.0,
                "reached": bool(gap_f <= REL_GAP),
            }
            if fused_info["reached"]:
                print(json.dumps({
                    "metric": "time_to_%s_subopt_%s_%dagents_r%d"
                              % (f"{REL_GAP:.0e}".replace("e-0", "e-"),
                                 _DSET, NUM_ROBOTS, RANK),
                    "value": round(dt_f, 3),
                    "unit": "s",
                    "rounds": rounds,
                    "f_opt": f_opt,
                    "rel_gap_reached": gap_f,
                    "ladder": {f"{REL_GAP:.0e}": {"s": round(dt_f, 3),
                                                  "rounds": rounds}},
                    "fused": fused_info,
                    "certified": certified,
                }))
                return
            # Verify missed the target: disclose, hand the VERIFIED
            # iterate to the round-4 refine/fallback machinery below
            # (its clock continues from here).
            log("  fused pipeline missed target — host-recenter fallback")
            fused_t0 = t0
        except Exception as e:  # noqa: BLE001 — degrade, don't abort
            log(f"  fused pipeline failed: {type(e).__name__}: {e} — "
                f"running the round-4 pipeline")
            fused_info = None

    # Ladder of relative gaps: record the first crossing time of each, so
    # TPU (float32: floor measured ~4e-6 on this problem) and CPU (float64)
    # compare at matching gaps down to each one's precision floor.
    ladder = [1e-3, 1e-4, 1e-5, REL_GAP]
    crossed: dict[float, tuple[float, int]] = {}
    if fused_info is None:
        state = state0  # fused-miss keeps ITS descended state + clock
    # On an f32 accelerator the re-centered refinement (below) continues
    # the descent without the precision floor AND (accelerated cycles)
    # faster per round, so hand off as soon as the remaining gap is
    # refinement territory instead of burning descent rounds: one
    # 200-round accelerated refine cycle covers two decades (measured,
    # experiments/refine_accel_cpu.py), so 1e-4 is early enough.  Ladder
    # rungs below the handoff are credited from the refine history.
    handoff = float(os.environ.get("BENCH_HANDOFF", "1e-4")) \
        if dtype == jnp.float32 else None
    if fused_info is not None:
        # Fused attempt ran and missed: its clock keeps running and its
        # VERIFIED iterate seeds the refine/fallback machinery below —
        # the descent loop is skipped entirely (f is already set).
        Xg64 = X64p
        t0 = fused_t0
        best = f
    else:
        f, Xg64 = eval_state(state)  # pre-clock: f defined if loop empty
        t0 = time.perf_counter()
        rounds = 0
        best = float("inf")
        gap_hist = []
        stall = 0
    while fused_info is None and rounds < MAX_ROUNDS:
        seg = FIRST_SEGMENT if rounds == 0 else EVAL_EVERY
        state, rounds = advance(rbcd, graph, meta, params, state, rounds,
                                seg)
        f, Xg64 = eval_state(state)  # device->host sync each eval
        now = time.perf_counter() - t0
        for g in ladder:
            if g not in crossed and f <= f_opt * (1.0 + g):
                crossed[g] = (now, rounds)
                log(f"  gap {g:.0e} at {now:.2f}s ({rounds} rounds)")
        if f <= target:
            break
        if handoff is not None and f <= f_opt * (1.0 + handoff) \
                and f / f_opt - 1.0 > 10.0 * REL_GAP:
            # Within a decade of the target, one more descent segment is
            # cheaper than a refine cycle's recenter + round-trips —
            # measured on torus3D, whose f32 floor is BELOW 1e-6: descent
            # crosses the target directly at 175 rounds / 0.67s where the
            # handoff-at-1.4e-6 path paid a 0.53s refine cycle for a
            # total of 0.90s.  The stall detector still catches problems
            # that floor above the target (sphere2500 floors at ~4e-6 and
            # DOES want the handoff — its gap at the handoff eval is
            # ~2e-5, an order above the 10x band).
            log(f"  handing off to refine at rel gap {f / f_opt - 1.0:.2e}")
            break
        # Stall detection: the f32 iterate has a precision floor above
        # 1e-6; stop once the cost stops improving instead of burning the
        # whole round budget at the floor.
        if f >= best * (1.0 - 1e-9):
            stall += 1
            if stall >= 4:
                log(f"  stalled at rel gap {f / f_opt - 1.0:.2e}")
                break
        else:
            stall = 0
        # Slope detection: a condition-limited graph (parking-garage)
        # never flat-stalls — it crawls monotonically.  Project the
        # rounds still needed from the contraction over a 4-eval WINDOW
        # (a single eval-to-eval delta is noise: accelerated descent is
        # non-monotone between restarts) and bail to the refine/fallback
        # path when even the remaining budget cannot cover it.
        gap_hist.append(max(f / f_opt - 1.0, 1e-300))
        if len(gap_hist) >= 4:
            import math as _math
            gap_now_d = gap_hist[-1]
            rate = _math.log10(max(gap_hist[-4] / gap_now_d,
                                   1.0 + 1e-12)) / 3.0
            need = _math.log10(gap_now_d / max(handoff or REL_GAP, REL_GAP))
            remaining_evals = max(MAX_ROUNDS - rounds, 0) / EVAL_EVERY
            if need > 0 and rate * remaining_evals < need:
                log(f"  contraction too slow ({rate:.2e} decades/eval over "
                    f"the last 4 evals at gap {gap_now_d:.2e}) — "
                    f"leaving descent")
                break
        best = min(best, f)
    gap = f / f_opt - 1.0
    dt = time.perf_counter() - t0
    log(f"  rounds {rounds}, final cost {f:.9f}, rel gap {gap:.2e}, "
        f"elapsed {dt:.2f}s")
    reached = crossed.get(REL_GAP, (None, rounds))[0]

    def centralized_fallback(Xg64_in, t_base):
        """Condition-limited-graph fallback (VERDICT r3 item 6): when the
        DISTRIBUTED refine cannot close the gap — the parking-garage
        signature, where block-coordinate descent itself stalls near 1e-3
        on both arms — continue with the SAME recentered-refine machinery
        on an A=1 graph: one block holds every pose, so each refine round
        is a centralized RTR step and the block-coordinate conditioning
        disappears, while the re-centering keeps dissolving the f32 floor.
        Returns a refine_res-shaped dict with its own wall offset."""
        import jax.numpy as jnp2
        from dpgo_tpu.config import AgentParams, Schedule, SolverParams
        from dpgo_tpu.models import rbcd as rbcd_mod
        from dpgo_tpu.models import refine as rmod
        from dpgo_tpu.utils.g2o import read_g2o
        from dpgo_tpu.utils.partition import partition_contiguous

        meas = read_g2o(DATASET)
        part1 = partition_contiguous(meas, 1)
        graph1, meta1 = rbcd_mod.build_graph(part1, RANK, jnp2.float32)
        params1 = AgentParams(
            d=meas.d, r=RANK, num_robots=1, schedule=Schedule.JACOBI,
            rel_change_tol=0.0,
            # The momentum horizon, not tCG depth, is the lever on the
            # condition-limited graphs that land here: the refine kernel's
            # single-step trust region stays at the Cauchy scale, so
            # deeper tCG hits the radius and stalls (measured on
            # parking-garage: inner=300/60-round cycles crawl at ~0.02
            # decades/cycle where inner=100/150-round cycles make 0.035),
            # while Nesterov contraction compounds over a long cycle.
            solver=SolverParams(grad_norm_tol=1e-9, max_inner_iters=100))
        t_r = time.perf_counter()
        X64_out, rgap, cycles, hist = rmod.solve_refine(
            Xg64_in, graph1, meta1, params1, edges_oracle, f_opt,
            rel_gap=REL_GAP, rounds_per_cycle=400, max_cycles=25,
            accel=True)
        fb_s = time.perf_counter() - t_r
        if os.environ.get("BENCH_SAVE_X"):
            np.save(os.environ["BENCH_SAVE_X"], np.asarray(X64_out))
        return {"refine_s": round(fb_s, 3), "cycles": cycles,
                "rel_gap": rgap, "reached": bool(rgap <= REL_GAP),
                "history": [[_finite_or_none(h), round(s, 3)]
                            for h, s in hist],
                "total_s": round(t_base + fb_s, 3)}

    # TPU-only path to the target gap: re-centered refinement
    # (``models.refine``) — the f64 reference lives on the host, the device
    # iterates only the small f32 correction, so the f32 floor dissolves
    # without leaving the accelerator's solve loop.
    refine_res = None
    fallback_res = None
    if reached is None and jax.devices()[0].platform != "cpu":
        try:
            import jax.numpy as jnp2
            # The handoff eval already read the full iterate back (the f32
            # arm's gap oracle IS the host f64 cost of that readback), so
            # the refine phase starts from Xg64 with no extra sync.
            # Compile the fused refine rounds outside the clock (bench.py
            # convention: steady-state timing, compile cached; num_rounds
            # is traced, so the 2-round warm-up covers REFINE_ROUNDS).
            ref_w = refine_mod.recenter(Xg64, graph, meta, params,
                                        edges_oracle)
            _ = np.asarray(refine_mod._refine_rounds_accel_jit(
                jnp2.zeros(ref_w.consts.R.shape, jnp2.float32),
                ref_w.consts, graph, meta, params, 2))
            # Adaptive cycle length, proportional to the decades of gap to
            # cover (DECADE_ROUNDS per decade — see its comment for the
            # measured contraction band); target 0.3x the requested gap so
            # a single cycle lands with margin, and the per-cycle f64
            # verify + extra-cycle fallback catches problems that contract
            # slower.
            import math
            decades = math.log10(max(f / f_opt - 1.0, REL_GAP)
                                 / (REL_GAP * 0.3))
            rpc = REFINE_ROUNDS or int(min(max(
                round(DECADE_ROUNDS * decades), 40), 220))
            t_r = time.perf_counter()
            _X64, rgap, cycles, hist = refine_mod.solve_refine(
                Xg64, graph, meta, params, edges_oracle, f_opt,
                rel_gap=REL_GAP, rounds_per_cycle=rpc,
                accel=True)
            refine_s = time.perf_counter() - t_r
            refine_res = {"refine_s": round(refine_s, 3),
                          "cycles": cycles, "rel_gap": rgap,
                          "reached": bool(rgap <= REL_GAP),
                          "history": [[_finite_or_none(h), round(s, 3)]
                                      for h, s in hist],
                          "total_s": round(dt + refine_s, 3)}
            log(f"  tpu-only refine: {refine_s:.2f}s, {cycles} cycles, "
                f"rel gap {rgap:.2e} -> total {dt + refine_s:.2f}s")
            # Credit ladder rungs crossed inside refinement: each history
            # entry is a VERIFIED f64 gap with its wall-clock offset, so
            # time-to-rung = descent time + offset of the first entry at
            # or below the rung.
            for g in ladder:
                if g not in crossed:
                    for h, s in hist:
                        if h <= g:
                            crossed[g] = (dt + s, rounds)
                            log(f"  gap {g:.0e} at {dt + s:.2f}s "
                                f"(refine)")
                            break
            if refine_res["reached"]:
                reached = dt + refine_s
                gap = rgap
            else:
                # Distributed refine exhausted its cycles above the target:
                # the condition-limited signature.  Hand the best verified
                # iterate to the centralized (A=1) continuation.
                log(f"  distributed refine stalled at {rgap:.2e} — "
                    f"centralized (A=1) fallback")
                fallback_res = centralized_fallback(_X64, dt + refine_s)
                log(f"  fallback: {fallback_res['refine_s']:.2f}s, "
                    f"{fallback_res['cycles']} cycles, rel gap "
                    f"{fallback_res['rel_gap']:.2e} -> total "
                    f"{fallback_res['total_s']:.2f}s")
                for g in ladder:
                    if g not in crossed:
                        for h, s in fallback_res["history"]:
                            if h <= g:
                                crossed[g] = (dt + refine_s + s, rounds)
                                break
                if fallback_res["reached"]:
                    reached = fallback_res["total_s"]
                    gap = fallback_res["rel_gap"]
        except Exception as e:  # noqa: BLE001 — auxiliary step
            log(f"  refine failed: {type(e).__name__}: {e}")
            if fallback_res is None and Xg64 is not None:
                # The centralized continuation does not depend on the
                # distributed refine having survived — run it from the
                # descent handoff iterate.
                try:
                    fallback_res = centralized_fallback(Xg64, dt)
                    log(f"  fallback (after refine failure): "
                        f"{fallback_res['refine_s']:.2f}s, rel gap "
                        f"{fallback_res['rel_gap']:.2e}")
                    if fallback_res["reached"]:
                        reached = fallback_res["total_s"]
                        gap = fallback_res["rel_gap"]
                except Exception as e2:  # noqa: BLE001
                    log(f"  fallback failed: {type(e2).__name__}: {e2}")

    # Hybrid fallback: when the accelerator's f32 iterate floors above the
    # target gap, hand the trajectory to a warm-started float64 CPU polish —
    # the pre-refine recipe, kept for comparison.
    hybrid = None
    if reached is None and jax.devices()[0].platform != "cpu":
        # The polish is auxiliary — any failure in it (timeout, bad output)
        # must not destroy the accelerator result gathered above.
        import subprocess
        import tempfile
        path = None
        try:
            with tempfile.NamedTemporaryFile(suffix=".npz",
                                             delete=False) as fh:
                np.savez(fh, X=np.asarray(state.X, np.float64))
                path = fh.name
            out = subprocess.run(
                [sys.executable, os.path.abspath(__file__)],
                env=dict(os.environ, BENCH_MODE="polish",
                         BENCH_POLISH_STATE=path, BENCH_F_OPT=repr(f_opt)),
                capture_output=True, text=True, timeout=1800)
            sys.stderr.write(out.stderr)
            if out.returncode == 0:
                pol = json.loads(out.stdout.strip().splitlines()[-1])
                hybrid = {"accel_s": round(dt, 3),
                          "polish_s": round(pol["polish_s"], 3),
                          "polish_rounds": pol["polish_rounds"],
                          "rel_gap": pol["rel_gap"],  # unrounded
                          "reached": pol["reached"],
                          "total_s": round(dt + pol["polish_s"], 3)}
                log(f"  hybrid total (accel + f64 polish): "
                    f"{hybrid['total_s']:.2f}s, reached={pol['reached']}")
        except Exception as e:  # noqa: BLE001 — auxiliary step
            log(f"  polish failed: {type(e).__name__}: {e}")
        finally:
            if path is not None and os.path.exists(path):
                os.unlink(path)
    print(json.dumps({
        # "1e-06" -> "1e-6": keep the historical metric key for default runs
        "metric": "time_to_%s_subopt_%s_%dagents_r%d"
                  % (f"{REL_GAP:.0e}".replace("e-0", "e-"),
                     _DSET, NUM_ROBOTS, RANK),
        "value": round(reached, 3) if reached is not None else None,
        "unit": "s",
        "rounds": rounds,
        "f_opt": f_opt,
        "rel_gap_reached": gap,
        "ladder": {f"{g:.0e}": {"s": round(t, 3), "rounds": r}
                   for g, (t, r) in sorted(crossed.items(), reverse=True)},
        "refine": refine_res,
        "fallback": fallback_res,
        "hybrid": hybrid,
        "fused": fused_info,
        "certified": certified,
    }))


if __name__ == "__main__":
    main()
