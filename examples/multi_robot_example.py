#!/usr/bin/env python
"""Multi-robot synchronous RBCD demo — the analog of the reference's
``multi-robot-example`` (``examples/MultiRobotExample.cpp``).

Partitions a g2o dataset into contiguous per-robot pose blocks, runs
synchronous RBCD (greedy block selection by default, like the reference
driver's argmax-gradient-norm selection at ``MultiRobotExample.cpp:242-256``;
``--schedule jacobi`` updates every agent each round, the TPU-native
default), with Nesterov acceleration on, r=5, and the reference demo's
termination gate (centralized Riemannian gradient norm < 0.1, at most 100
iterations — ``MultiRobotExample.cpp:56-58,238``).  Tracks the communication
volume the exchange would cost on a real network the way the reference driver
does (lifting-matrix broadcast + pose dictionaries + global anchor,
``MultiRobotExample.cpp:60,143,195,209,274-279``).

Usage:
    python examples/multi_robot_example.py NUM_ROBOTS DATASET.g2o [LOG_DIR]
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from _common import setup_jax  # noqa: E402


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("num_robots", type=int)
    ap.add_argument("dataset", help="input .g2o file")
    ap.add_argument("log_dir", nargs="?", default=None,
                    help="optional output directory for CSV logs")
    ap.add_argument("--rank", type=int, default=5)
    ap.add_argument("--max-iters", type=int, default=100)
    ap.add_argument("--grad-norm-tol", type=float, default=0.1)
    ap.add_argument("--schedule",
                    choices=["greedy", "jacobi", "async", "colored"],
                    default="greedy")
    ap.add_argument("--eval-every", type=int, default=1,
                    help="rounds between centralized cost/gradnorm evals "
                    "(1 = the reference demo's per-iteration printout, "
                    "MultiRobotExample.cpp:231-235; each eval is a "
                    "device-to-host sync, the dominant per-round cost on "
                    "a remote accelerator)")
    ap.add_argument("--no-acceleration", action="store_true")
    ap.add_argument("--robust", action="store_true",
                    help="enable the GNC_TLS robust outer loop")
    ap.add_argument("--f32", action="store_true",
                    help="float32 state (TPU-native dtype; default float64)")
    ap.add_argument("--telemetry", default=None, metavar="RUN_DIR",
                    help="enable run-scoped telemetry (dpgo_tpu.obs): "
                    "JSONL events + metrics snapshot under RUN_DIR, and a "
                    "rendered run report after the solve")
    args = ap.parse_args()

    setup_jax(force_x64_on_cpu=not args.f32)
    import jax.numpy as jnp
    import numpy as np

    from dpgo_tpu.config import AgentParams, RobustCostParams, RobustCostType, Schedule
    from dpgo_tpu.models import rbcd
    from dpgo_tpu.utils import logger
    from dpgo_tpu.utils.g2o import read_g2o
    from dpgo_tpu.utils.partition import partition_contiguous

    dtype = jnp.float32 if args.f32 else jnp.float64

    meas = read_g2o(args.dataset)
    print(f"Loaded {len(meas)} measurements over {meas.num_poses} poses "
          f"(SE({meas.d})) from {args.dataset}")

    params = AgentParams(
        d=meas.d, r=args.rank, num_robots=args.num_robots,
        acceleration=not args.no_acceleration,
        schedule={"greedy": Schedule.GREEDY, "jacobi": Schedule.JACOBI,
                  "async": Schedule.ASYNC,
                  "colored": Schedule.COLORED}[args.schedule],
        robust=RobustCostParams(
            cost_type=RobustCostType.GNC_TLS if args.robust
            else RobustCostType.L2),
    )

    part = partition_contiguous(meas, args.num_robots)

    run = None
    if args.telemetry:
        from dpgo_tpu import obs
        run = obs.start_run(args.telemetry)
        run.event("example_start", phase="setup",
                  example="multi_robot_example", dataset=args.dataset,
                  num_robots=args.num_robots, rank=args.rank,
                  schedule=args.schedule, robust=args.robust)
        # Dataset identity for report --compare's apples-to-oranges gate
        # (the solver fingerprints everything else it knows).
        run.set_fingerprint(dataset=args.dataset)

    t0 = time.perf_counter()
    result = rbcd.solve_rbcd(
        meas, args.num_robots, params=params, max_iters=args.max_iters,
        grad_norm_tol=args.grad_norm_tol, eval_every=args.eval_every,
        dtype=dtype, part=part)
    dt = time.perf_counter() - t0

    # --- Communication accounting (model of MultiRobotExample.cpp's byte
    # counters; 8 bytes per double as in the reference's Matrix payloads).
    # Per-robot neighbor-slot counts = distinct remote (robot, pose) pairs
    # referenced by shared edges (host-side, from the partition alone).
    cls = part.classify()
    nbr_slots = np.zeros(args.num_robots, int)
    shared = np.nonzero(cls == 2)[0]
    m = part.meas
    # One vectorized pass: for each shared edge, each endpoint robot
    # references the remote (robot, pose) pair; count distinct pairs per
    # referencing robot.
    if shared.size:
        ref_robot = np.concatenate([m.r1[shared], m.r2[shared]])
        remote = np.stack([
            np.concatenate([m.r2[shared], m.r1[shared]]),
            np.concatenate([m.p2[shared], m.p1[shared]]),
        ], axis=1)
        triples = np.unique(np.column_stack([ref_robot, remote]), axis=0)
        robots, counts = np.unique(triples[:, 0], return_counts=True)
        nbr_slots[robots] = counts
    else:
        triples = np.zeros((0, 3), int)

    BYTES = 8
    r, d = args.rank, meas.d
    pose_msg = r * (d + 1) * BYTES  # one lifted pose block
    aux_factor = 2 if params.acceleration else 1  # aux poses Y exchanged too
    # One selected receiver per round in the reference's greedy model; every
    # agent receives each round under jacobi/async.
    recv = int(nbr_slots.max()) if params.schedule == Schedule.GREEDY \
        else int(nbr_slots.sum())
    total_bytes = (
        # Lifting-matrix broadcast from robot 0 (MultiRobotExample.cpp:139-146).
        (args.num_robots - 1) * r * d * BYTES
        + result.iterations * (
            recv * pose_msg * aux_factor
            # Global anchor broadcast each round (MultiRobotExample.cpp:258-263).
            + (args.num_robots - 1) * pose_msg))

    for it, (f, gn) in enumerate(zip(result.cost_history,
                                     result.grad_norm_history)):
        rnd = min((it + 1) * args.eval_every, result.iterations)
        print(f"iter {rnd:4d}: cost {f:.6f}  gradnorm {gn:.6f}")
    print(f"Terminated by {result.terminated_by} after {result.iterations} "
          f"iterations in {dt:.2f}s "
          f"({result.iterations / dt:.2f} rounds/s)")
    print(f"Total communication bytes (model): {total_bytes}")

    if run is not None:
        # Per-neighbor exchange volume (the reference driver's hand-counted
        # bytes, broken down by edge): one pose message per neighbor slot
        # per exchange round under jacobi/async; greedy serializes receivers
        # but the per-pair volume model is the same.
        pairs, pair_slots = (np.unique(triples[:, :2], axis=0,
                                       return_counts=True)
                             if triples.size else (np.zeros((0, 2), int), []))
        c_nbr = run.counter("comms_bytes_model",
                            "modeled pose-exchange bytes received over the "
                            "run, per (robot, neighbor)", unit="bytes")
        for (a, b), slots in zip(pairs, pair_slots):
            c_nbr.inc(int(slots) * pose_msg * aux_factor * result.iterations,
                      robot=int(a), neighbor=int(b))
        run.metric("total_communication_bytes", total_bytes, "bytes",
                   phase="report")
        run.metric("solve_wall_clock_seconds", dt, "s", phase="report",
                   rounds_per_sec=result.iterations / max(dt, 1e-9))

    if args.log_dir:
        os.makedirs(args.log_dir, exist_ok=True)
        if meas.d == 3:
            logger.log_trajectory(
                np.asarray(result.T),
                os.path.join(args.log_dir, "trajectory_optimized.csv"))
        out = os.path.join(args.log_dir, "dpgo_total_communication_bytes.txt")
        with open(out, "w") as f:
            f.write(f"{total_bytes}\n")
        print(f"Logs written to {args.log_dir}")

    if run is not None:
        from dpgo_tpu import obs
        from dpgo_tpu.obs.report import render_report
        obs.end_run()
        print()
        print(render_report(run.run_dir))
        print(f"\nTelemetry artifacts in {run.run_dir} — re-render with: "
              f"python -m dpgo_tpu.obs.report {run.run_dir}")


if __name__ == "__main__":
    main()
