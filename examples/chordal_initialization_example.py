#!/usr/bin/env python
"""Chordal initialization demo — the analog of the reference's
``chordal-initialization-example`` (``examples/ChordalInitializationExample.cpp``):
load a g2o dataset, run the centralized chordal initialization (rotation
relaxation + translation recovery, on TPU via CG instead of SPQR —
``dpgo_tpu/ops/chordal.py``), and report the cost of the initial guess.

Usage:
    python examples/chordal_initialization_example.py DATASET.g2o
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from _common import setup_jax  # noqa: E402


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("dataset", help="input .g2o file")
    ap.add_argument("--log-dir", default=None)
    args = ap.parse_args()

    jax = setup_jax()
    import jax.numpy as jnp
    import numpy as np

    from dpgo_tpu.ops import chordal, quadratic
    from dpgo_tpu.types import edge_set_from_measurements
    from dpgo_tpu.utils import logger
    from dpgo_tpu.utils.g2o import read_g2o

    meas = read_g2o(args.dataset)
    print(f"Loaded {len(meas)} measurements over {meas.num_poses} poses "
          f"(SE({meas.d})) from {args.dataset}")

    dtype = jnp.float64 if jax.config.jax_enable_x64 else jnp.float32
    edges = edge_set_from_measurements(meas, dtype=dtype)

    t0 = time.perf_counter()
    T0 = chordal.chordal_initialization(edges, meas.num_poses)
    T0.block_until_ready()
    dt = time.perf_counter() - t0
    cost = float(quadratic.cost(T0, edges))
    print(f"Chordal initialization: cost {cost:.6f} in {dt:.2f}s")

    if args.log_dir:
        os.makedirs(args.log_dir, exist_ok=True)
        if meas.d == 3:
            logger.log_trajectory(
                np.asarray(T0),
                os.path.join(args.log_dir, "trajectory_initial.csv"))
        print(f"Logs written to {args.log_dir}")


if __name__ == "__main__":
    main()
