#!/usr/bin/env python
"""Asynchronous multi-robot deployment demo — the RA-L 2020 operating mode,
on the fault-tolerant comms subsystem.

Each robot is a ``PGOAgent`` with its own Poisson-clock optimization thread
(``start_optimization_loop``), while the network is an in-process
``dpgo_tpu.comms`` fleet: every robot talks to a ``RoundBus`` hub over a
``LoopbackTransport`` pair through a ``ReliableChannel`` (deadlines,
sequence numbers, stale-frame drops), exactly the stack the TCP example
runs over sockets.  No global barrier — agents fire on their own clocks
against whatever neighbor poses last arrived, which is precisely the
regime the RA-L 2020 convergence result covers.

Faults are injectable (seeded drop / delay / reorder / corrupt), and a
robot can be killed mid-run (``--kill-robot R --kill-at T``): the bus
detects the closed transport, announces it, survivors freeze its cached
poses, exclude it from the termination quorum, and still reach consensus.

Usage:
    python examples/async_deployment_example.py NUM_ROBOTS DATASET.g2o
        [--rate-hz 20] [--comm-hz 10] [--timeout 30] [--log-dir DIR]
        [--fault-drop P] [--fault-delay P] [--fault-seed N]
        [--kill-robot R --kill-at T]
"""

from __future__ import annotations

import argparse
import os
import sys
import threading
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from _common import setup_jax  # noqa: E402


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("num_robots", type=int)
    ap.add_argument("dataset", help="input .g2o file")
    ap.add_argument("--rank", type=int, default=5)
    ap.add_argument("--rate-hz", type=float, default=20.0,
                    help="per-agent Poisson clock rate")
    ap.add_argument("--comm-hz", type=float, default=10.0,
                    help="network (pose/status shuttle) frequency")
    ap.add_argument("--timeout", type=float, default=30.0,
                    help="wall-clock budget in seconds")
    ap.add_argument("--log-dir", default=None)
    ap.add_argument("--telemetry", default=None, metavar="DIR",
                    help="scope a dpgo_tpu.obs run here: metrics, events, "
                         "and distributed-tracing spans; a Perfetto-"
                         "loadable DIR/trace.json and the fleet report "
                         "are emitted after the run")
    ap.add_argument("--staleness", type=int, default=1,
                    help="network-loop overlap bound: >=1 double-buffers "
                         "each robot's publish/collect against its "
                         "optimizer (default 1 — async mode has no "
                         "lockstep to preserve); 0 reverts to "
                         "publish-then-wait per tick")
    ap.add_argument("--wire-dtype", choices=("f64", "f32", "bf16"),
                    default="f64",
                    help="pose payload dtype on the wire (bf16 halves "
                         "pose bytes vs f32, f32-accumulated on receipt)")
    ap.add_argument("--fault-drop", type=float, default=0.0)
    ap.add_argument("--fault-delay", type=float, default=0.0)
    ap.add_argument("--fault-delay-s", type=float, nargs=2,
                    default=[0.05, 0.3], metavar=("MIN", "MAX"))
    ap.add_argument("--fault-reorder", type=float, default=0.0)
    ap.add_argument("--fault-corrupt", type=float, default=0.0)
    ap.add_argument("--fault-seed", type=int, default=0)
    ap.add_argument("--kill-robot", type=int, default=None,
                    help="kill this robot's comms + optimizer mid-run")
    ap.add_argument("--kill-at", type=float, default=None,
                    help="seconds into the run at which --kill-robot dies")
    args = ap.parse_args()
    if args.rate_hz <= 0 or args.comm_hz <= 0:
        ap.error("--rate-hz and --comm-hz must be positive")
    if args.kill_robot is not None and args.kill_at is None:
        ap.error("--kill-robot requires --kill-at")

    setup_jax()

    from dpgo_tpu import obs
    from dpgo_tpu.agent import PGOAgent
    from dpgo_tpu.comms import (FaultInjector, FaultSpec, RetryPolicy,
                                TransportClosed, apply_peer_frame,
                                loopback_fleet, pack_agent_frame)
    from dpgo_tpu.config import AgentParams
    from dpgo_tpu.utils.g2o import read_g2o
    from dpgo_tpu.utils.partition import agent_measurements, \
        partition_contiguous

    run = obs.start_run(args.telemetry) if args.telemetry else None

    meas = read_g2o(args.dataset)
    print(f"Loaded {len(meas)} measurements over {meas.num_poses} poses "
          f"(SE({meas.d})) from {args.dataset}")

    params = AgentParams(
        d=meas.d, r=args.rank, num_robots=args.num_robots,
        acceleration=False,  # async forbids acceleration (PGOAgent.cpp:863)
        log_data=args.log_dir is not None,
        log_directory=args.log_dir or "")
    part = partition_contiguous(meas, args.num_robots)
    agents = [PGOAgent(a, params) for a in range(args.num_robots)]
    for ag in agents[1:]:
        ag.set_lifting_matrix(agents[0].get_lifting_matrix())
    for ag in agents:
        ag.set_pose_graph(*agent_measurements(part, ag.robot_id))

    spec = FaultSpec(drop=args.fault_drop, delay=args.fault_delay,
                     delay_s=tuple(args.fault_delay_s),
                     reorder=args.fault_reorder, corrupt=args.fault_corrupt)
    injector = FaultInjector(spec, seed=args.fault_seed) \
        if spec.any_active() else None
    tick = 1.0 / args.comm_hz
    policy = RetryPolicy(send_timeout_s=tick, recv_timeout_s=2 * tick)
    bus, clients = loopback_fleet(
        args.num_robots, injector=injector, policy=policy,
        round_timeout_s=2 * tick, miss_limit=5,
        liveness_timeout_s=max(1.0, 10 * tick))
    stop = threading.Event()

    def bus_loop():
        while not stop.is_set():
            bus.round()
        # One last broadcast flushes pending `_lost` knowledge.

    def robot_loop(ag: PGOAgent):
        """One network tick per iteration: publish status + public poses
        (packed columnar wire), collect the broadcast, ingest peers
        (sequence-checked), track lost robots.  A missed broadcast skips
        one update — never a hang.  With --staleness >= 1 the
        publish/collect round runs on the client's overlap thread so this
        loop never blocks the tick cadence on the wire."""
        rid = ag.robot_id
        client = clients[rid]
        client.channel.start_heartbeat(tick / 2)
        if args.staleness > 0:
            client.start_overlap(args.staleness, timeout=2 * tick)
        while not stop.is_set():
            frame = pack_agent_frame(ag, include_anchor=(rid == 0),
                                     wire_dtype=args.wire_dtype)
            try:
                merged = client.exchange(frame, timeout=2 * tick)
            except TransportClosed:
                return  # killed, or the run is over
            if merged is not None:
                for peer, pf in client.peer_frames(merged).items():
                    apply_peer_frame(ag, peer, pf,
                                     accept_anchor=(rid != 0 and peer == 0))
                for lost in client.lost:
                    ag.mark_neighbor_lost(lost)
            time.sleep(tick)

    threads = [threading.Thread(target=bus_loop, daemon=True)]
    threads += [threading.Thread(target=robot_loop, args=(ag,), daemon=True)
                for ag in agents]
    for t in threads:
        t.start()
    for ag in agents:
        ag.start_optimization_loop(rate_hz=args.rate_hz)
    print(f"{args.num_robots} agents optimizing asynchronously at "
          f"~{args.rate_hz} Hz, network at {args.comm_hz} Hz"
          + (", faults live" if injector is not None else ""))

    killed: set[int] = set()
    t0 = time.perf_counter()
    try:
        while time.perf_counter() - t0 < args.timeout:
            time.sleep(tick)
            now = time.perf_counter() - t0
            if (args.kill_robot is not None and now >= args.kill_at
                    and args.kill_robot not in killed):
                rid = args.kill_robot
                killed.add(rid)
                agents[rid].end_optimization_loop()
                clients[rid].close()  # the bus sees a dead transport
                print(f"[{now:5.1f}s] robot {rid} killed")
            live = [ag for ag in agents if ag.robot_id not in killed]
            if all(ag.get_status().ready_to_terminate for ag in live) and \
                    live[0].should_terminate():
                print("Team consensus reached"
                      + (f" (without robot(s) {sorted(killed)})" if killed
                         else "") + ".")
                break
    finally:
        stop.set()
        for ag in agents:
            ag.end_optimization_loop()
        for t in threads:
            t.join(timeout=5)
        bus.close()
        for c in clients.values():
            c.close()

    dt = time.perf_counter() - t0
    iters = [ag.get_status().iteration_number for ag in agents]
    costs = [ag.local_cost() for ag in agents]
    totals = bus.totals()
    print(f"Stopped after {dt:.1f}s; per-agent iterations {iters} "
          f"(no barrier — counts differ by design)")
    print("Per-agent local costs:",
          [f"{c:.3f}" if c is not None else "n/a" for c in costs])
    print(f"Bus: {totals.messages_received} frames in / "
          f"{totals.messages_sent} out, {totals.timeouts} timeouts, "
          f"{totals.stale_dropped} stale dropped, "
          f"{totals.corrupt_dropped} corrupt dropped; "
          f"lost robots {sorted(bus.lost)}")
    if args.log_dir:
        for ag in agents:
            if ag.robot_id not in killed:
                ag.log_trajectory()
        print(f"Per-robot dumps under {args.log_dir}/robot*/")
    if run is not None:
        obs.end_run()
        from dpgo_tpu.obs import timeline
        from dpgo_tpu.obs.report import render_report
        trace_path = os.path.join(args.telemetry, "trace.json")
        timeline.write_chrome_trace(trace_path,
                                    timeline.merge([args.telemetry]))
        print(render_report(args.telemetry), file=sys.stderr)
        print(f"Perfetto timeline: {trace_path} "
              "(open in https://ui.perfetto.dev)")


if __name__ == "__main__":
    main()
