#!/usr/bin/env python
"""Asynchronous multi-robot deployment demo — the RA-L 2020 operating mode.

Each robot is a ``PGOAgent`` with its own Poisson-clock optimization thread
(``start_optimization_loop``, the analog of reference
``PGOAgent.cpp:861-916``), while this driver plays the network the way the
external ``dpgo_ros`` wrapper does in the reference's deployments: it
periodically shuttles public-pose dictionaries and gossiped statuses
between agents until team consensus (``should_terminate``).  No global
barrier — every agent fires on its own clock against whatever neighbor
poses it last received.

Usage:
    python examples/async_deployment_example.py NUM_ROBOTS DATASET.g2o
        [--rate-hz 20] [--comm-hz 10] [--timeout 30] [--log-dir DIR]
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from _common import setup_jax  # noqa: E402


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("num_robots", type=int)
    ap.add_argument("dataset", help="input .g2o file")
    ap.add_argument("--rank", type=int, default=5)
    ap.add_argument("--rate-hz", type=float, default=20.0,
                    help="per-agent Poisson clock rate")
    ap.add_argument("--comm-hz", type=float, default=10.0,
                    help="network (pose/status shuttle) frequency")
    ap.add_argument("--timeout", type=float, default=30.0,
                    help="wall-clock budget in seconds")
    ap.add_argument("--log-dir", default=None)
    args = ap.parse_args()
    if args.rate_hz <= 0 or args.comm_hz <= 0:
        ap.error("--rate-hz and --comm-hz must be positive")

    setup_jax()

    from dpgo_tpu.agent import PGOAgent
    from dpgo_tpu.config import AgentParams
    from dpgo_tpu.utils.g2o import read_g2o
    from dpgo_tpu.utils.partition import agent_measurements, \
        partition_contiguous

    meas = read_g2o(args.dataset)
    print(f"Loaded {len(meas)} measurements over {meas.num_poses} poses "
          f"(SE({meas.d})) from {args.dataset}")

    params = AgentParams(
        d=meas.d, r=args.rank, num_robots=args.num_robots,
        acceleration=False,  # async forbids acceleration (PGOAgent.cpp:863)
        log_data=args.log_dir is not None,
        log_directory=args.log_dir or "")
    part = partition_contiguous(meas, args.num_robots)
    agents = [PGOAgent(a, params) for a in range(args.num_robots)]
    for ag in agents[1:]:
        ag.set_lifting_matrix(agents[0].get_lifting_matrix())
    for ag in agents:
        ag.set_pose_graph(*agent_measurements(part, ag.robot_id))

    def shuttle():
        """One network tick: all-to-all pose + status gossip and the
        global-anchor broadcast (what dpgo_ros pub/sub carries)."""
        dicts = [ag.get_shared_pose_dict() for ag in agents]
        stats = [ag.get_status() for ag in agents]
        anchor = agents[0].get_global_anchor()
        for dst in agents:
            for src_id in range(args.num_robots):
                if src_id != dst.robot_id:
                    dst.update_neighbor_poses(src_id, dicts[src_id])
                    dst.set_neighbor_status(stats[src_id])
            if anchor is not None:
                dst.set_global_anchor(anchor)

    # Initialization messages flow over the same network as everything else;
    # agents enter INITIALIZED as robust frame alignment succeeds.
    shuttle()
    for ag in agents:
        ag.start_optimization_loop(rate_hz=args.rate_hz)
    print(f"{args.num_robots} agents optimizing asynchronously at "
          f"~{args.rate_hz} Hz, network at {args.comm_hz} Hz")

    t0 = time.perf_counter()
    try:
        while time.perf_counter() - t0 < args.timeout:
            time.sleep(1.0 / args.comm_hz)
            shuttle()
            if all(ag.get_status().ready_to_terminate for ag in agents) and \
                    agents[0].should_terminate():
                print("Team consensus reached.")
                break
    finally:
        for ag in agents:
            ag.end_optimization_loop()

    dt = time.perf_counter() - t0
    iters = [ag.get_status().iteration_number for ag in agents]
    costs = [ag.local_cost() for ag in agents]
    print(f"Stopped after {dt:.1f}s; per-agent iterations {iters} "
          f"(no barrier — counts differ by design)")
    print("Per-agent local costs:",
          [f"{c:.3f}" if c is not None else "n/a" for c in costs])
    if args.log_dir:
        for ag in agents:
            ag.log_trajectory()
        print(f"Per-robot dumps under {args.log_dir}/robot*/")


if __name__ == "__main__":
    main()
