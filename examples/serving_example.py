"""Serving-plane example: many tenants, one batched PGO backend.

Synthesizes mixed-size pose graphs for a handful of tenants, stands up an
in-process ``SolveServer`` (and optionally the TCP front-end), submits
everything concurrently, and prints each tenant's results plus — with
``--telemetry`` — the per-tenant SLO section of the run report.

::

    JAX_PLATFORMS=cpu python examples/serving_example.py \
        --problems 6 --tenants 3 --telemetry /tmp/serve_example

    # TCP variant: requests travel as g2o payloads over packed frames.
    JAX_PLATFORMS=cpu python examples/serving_example.py --tcp
"""

from __future__ import annotations

import argparse
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from _common import setup_jax  # noqa: E402

setup_jax()

import numpy as np  # noqa: E402

from dpgo_tpu import obs  # noqa: E402
from dpgo_tpu.config import AgentParams  # noqa: E402
from dpgo_tpu.serve import SolveRequest, SolveServer  # noqa: E402
from dpgo_tpu.serve.frontend import ServeFrontend, solve_g2o  # noqa: E402
from dpgo_tpu.utils.g2o import write_g2o  # noqa: E402
from dpgo_tpu.utils.synthetic import make_measurements  # noqa: E402


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--problems", type=int, default=6)
    ap.add_argument("--tenants", type=int, default=3)
    ap.add_argument("--robots", type=int, default=2)
    ap.add_argument("--base-n", type=int, default=36)
    ap.add_argument("--max-iters", type=int, default=10)
    ap.add_argument("--tcp", action="store_true",
                    help="submit over the TCP front-end (g2o upload)")
    ap.add_argument("--max-frame-mb", type=float, default=64.0)
    ap.add_argument("--telemetry", metavar="DIR", default=None)
    ap.add_argument("--metrics-port", type=int, default=None,
                    help="live /metrics,/healthz,/statusz sidecar "
                         "(requires --telemetry); the example scrapes "
                         "/statusz once and prints it")
    ap.add_argument("--slo-latency-s", type=float, default=None,
                    help="latency objective -> burn-rate SLO alerting")
    args = ap.parse_args(argv)

    problems = []
    for k in range(args.problems):
        meas, _ = make_measurements(
            np.random.default_rng(k), n=args.base_n + 3 * k, d=3,
            num_lc=6 + k % 4, rot_noise=0.01, trans_noise=0.01)
        problems.append(meas)
    params = AgentParams(d=3, r=5, num_robots=args.robots)

    scope = obs.run_scope(args.telemetry) if args.telemetry else None
    if scope:
        scope.__enter__()
    try:
        from dpgo_tpu.serve import ServeSLO

        with SolveServer(max_batch=8, batch_window_s=0.02, quantum=64,
                         metrics_port=args.metrics_port,
                         slo=ServeSLO(latency_s=args.slo_latency_s)
                         if args.slo_latency_s is not None else None) as srv:
            if args.tcp:
                with ServeFrontend(
                        srv,
                        max_frame_bytes=int(args.max_frame_mb * 2 ** 20)
                ) as fe:
                    print(f"TCP front-end on {fe.host}:{fe.port}")
                    for k, meas in enumerate(problems):
                        with tempfile.NamedTemporaryFile(
                                suffix=".g2o", mode="w", delete=False) as fh:
                            path = fh.name
                        write_g2o(meas, path)
                        out = solve_g2o(
                            "127.0.0.1", fe.port, path,
                            num_robots=args.robots,
                            tenant=f"tenant{k % args.tenants}",
                            max_iters=args.max_iters, eval_every=5,
                            grad_norm_tol=1e-12)
                        print(f"  tenant{k % args.tenants} problem {k}: "
                              f"ok={out['ok']} cost="
                              f"{out['cost_history'][-1]:.6f} "
                              f"({out['iterations']} rounds, "
                              f"{out['terminated_by']})")
            else:
                tickets = [
                    srv.submit(SolveRequest(
                        meas=meas, num_robots=args.robots, params=params,
                        tenant=f"tenant{k % args.tenants}",
                        max_iters=args.max_iters, grad_norm_tol=1e-12,
                        eval_every=5))
                    for k, meas in enumerate(problems)
                ]
                for k, t in enumerate(tickets):
                    res = t.result(timeout=600)
                    print(f"  tenant{k % args.tenants} problem {k}: cost="
                          f"{res.cost_history[-1]:.6f} "
                          f"({res.iterations} rounds, {res.terminated_by}, "
                          f"waited {t.queue_wait_s * 1e3:.1f}ms)")
            print(f"executable cache: {srv.cache.stats()}")
            if srv.sidecar is not None:
                # The same JSON `report --live HOST:PORT` renders.
                from dpgo_tpu.obs.report import render_statusz

                print(f"live endpoints on {srv.sidecar.host}:"
                      f"{srv.sidecar.port} (/metrics /healthz /statusz)")
                print(render_statusz(srv.status()))
    finally:
        if scope:
            scope.__exit__(None, None, None)
    if args.telemetry:
        from dpgo_tpu.obs.report import render_report

        print(render_report(args.telemetry))
    return 0


if __name__ == "__main__":
    sys.exit(main())
