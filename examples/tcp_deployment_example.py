#!/usr/bin/env python
"""N-process TCP deployment demo: the message vocabulary over a real wire.

The reference defers multi-process transport to the external ``dpgo_ros``
wrapper (``/root/reference/README.md:40-42``); the in-repo demos (ours and
the reference's) drive agents in one process.  This example goes further
than the reference's in-repo story: each robot is its own OS process
holding one ``PGOAgent``, and the deployment message set —
``get_shared_pose_dict`` / ``update_neighbor_poses``, status gossip, GNC
weight publication (``get_shared_weight_dict`` /
``update_shared_weights``), lifting-matrix and global-anchor broadcast —
travels over localhost TCP as length-prefixed ``npz`` frames.  The
launcher doubles as the message bus (the pub/sub role dpgo_ros plays):
it accepts one connection per robot and re-broadcasts every round's
frames to all peers, so the same code runs 2 robots or N.

Modes:

* ``--mode sync`` (default): each robot takes one ``iterate()`` per bus
  round — the deterministic in-process loop of
  ``examples/MultiRobotExample.cpp`` stretched over processes.
* ``--mode async``: each robot runs its Poisson-clock optimization
  thread (``start_optimization_loop``, reference ``PGOAgent.cpp:861-898``)
  while the main thread exchanges poses at the bus cadence — the RA-L
  2020 deployment model: iteration and communication fully decoupled.

Usage (launcher spawns all robot processes and assembles the result):
    python examples/tcp_deployment_example.py DATASET.g2o \
        [--robots 2] [--rank 5] [--rounds 120] [--mode sync|async] \
        [--robust] [--port 0] [--out-dir DIR]

Internal per-robot entry (what the launcher spawns):
    ... --robot ID --port P
"""

from __future__ import annotations

import argparse
import io
import json
import os
import socket
import struct
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from _common import setup_jax  # noqa: E402


# ---------------------------------------------------------------------------
# Wire format: length-prefixed npz frames (arrays only — no pickle)
# ---------------------------------------------------------------------------

def send_frame(sock: socket.socket, arrays: dict) -> int:
    buf = io.BytesIO()
    np.savez(buf, **arrays)
    data = buf.getvalue()
    sock.sendall(struct.pack("<Q", len(data)) + data)
    return 8 + len(data)


def recv_frame(sock: socket.socket) -> dict:
    def recv_exact(k):
        chunks = []
        while k:
            c = sock.recv(k)
            if not c:
                raise ConnectionError("peer closed")
            chunks.append(c)
            k -= len(c)
        return b"".join(chunks)

    (length,) = struct.unpack("<Q", recv_exact(8))
    return dict(np.load(io.BytesIO(recv_exact(length))))


def pack_pose_dict(prefix: str, pose_dict: dict) -> dict:
    return {f"{prefix}_{r}_{p}": np.asarray(block)
            for (r, p), block in pose_dict.items()}


def unpack_pose_dict(frame: dict, prefix: str) -> dict:
    out = {}
    for key, arr in frame.items():
        if key.startswith(prefix + "_"):
            _, r, p = key.rsplit("_", 2)
            out[(int(r), int(p))] = arr
    return out


# ---------------------------------------------------------------------------
# One robot process
# ---------------------------------------------------------------------------

def _dial_bus(robot_id: int, port: int, out_dir: str) -> socket.socket:
    """Connect to the launcher's bus; with ``port`` 0 the OS-assigned
    choice is read from out_dir/port.txt (published atomically by the
    launcher after binding — no pick-then-rebind TOCTOU window)."""
    port_file = os.path.join(out_dir, "port.txt")
    dial = port
    for _ in range(100):
        if port == 0:
            # Re-read every attempt: a stale file from a previous run may
            # be consumed before this run's launcher republishes.
            try:
                with open(port_file) as fh:
                    dial = int(fh.read())
            except (FileNotFoundError, ValueError):
                time.sleep(0.1)
                continue
        try:
            conn = socket.create_connection(("127.0.0.1", dial))
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            send_frame(conn, {"hello": np.asarray(robot_id, np.int64)})
            return conn
        except ConnectionRefusedError:
            time.sleep(0.1)
    where = f"port {dial}" if dial else f"port file {port_file}"
    raise ConnectionError(f"robot {robot_id} could not reach the bus "
                          f"({where})")


def run_robot(robot_id: int, dataset: str, num_robots: int, rank: int,
              rounds: int, port: int, out_dir: str, mode: str,
              robust: bool, async_rate: float,
              telemetry: bool = False) -> None:
    setup_jax()
    from dpgo_tpu import obs
    from dpgo_tpu.agent import AgentState, PGOAgent, PGOAgentStatus
    from dpgo_tpu.config import AgentParams, RobustCostParams, RobustCostType
    from dpgo_tpu.utils.g2o import read_g2o
    from dpgo_tpu.utils.partition import agent_measurements, \
        partition_contiguous

    # Each robot process scopes its own telemetry run (one run dir per
    # robot, like the reference's one-logDirectory-per-process layout);
    # once ambient, the PGOAgent hot paths (iterate latency, per-neighbor
    # comms bytes, GNC weight updates) record into it automatically.
    run = obs.start_run(
        os.path.join(out_dir, "telemetry", f"robot{robot_id}")) \
        if telemetry else None

    meas = read_g2o(dataset)
    rp = RobustCostParams(cost_type=RobustCostType.GNC_TLS) if robust \
        else RobustCostParams()
    params = AgentParams(d=meas.d, r=rank, num_robots=num_robots, robust=rp)
    part = partition_contiguous(meas, num_robots)
    agent = PGOAgent(robot_id, params)

    conn = _dial_bus(robot_id, port, out_dir)

    # Lifting-matrix broadcast (robot 0 self-generates; reference
    # MultiRobotExample.cpp:139-146) — rides the first bus round.
    if robot_id == 0:
        first = {"ylift": agent.get_lifting_matrix()}
    else:
        first = {}
    send_frame(conn, first)
    merged = recv_frame(conn)
    if robot_id != 0:
        agent.set_lifting_matrix(merged["r0|ylift"])
    agent.set_pose_graph(*agent_measurements(part, robot_id))

    if mode == "async":
        agent.start_optimization_loop(rate_hz=async_rate)

    bytes_sent = 0
    for it in range(rounds):
        st = agent.get_status()
        frame = {"status": np.asarray(
            [st.robot_id, st.state.value, st.instance_number,
             st.iteration_number, int(st.ready_to_terminate)], np.int64),
            "relchange": np.asarray(st.relative_change, np.float64)}
        frame.update(pack_pose_dict("pose", agent.get_shared_pose_dict()))
        if robust:
            # GNC weight publication (reference mPublishWeightsRequested,
            # consumed by dpgo_ros): owner pushes shared-edge weights.
            wd = agent.get_shared_weight_dict()
            frame.update({
                f"wt_{r1}_{p1}_{r2}_{p2}": np.asarray(w, np.float64)
                for ((r1, p1), (r2, p2)), w in wd.items()})
        if robot_id == 0:
            anchor = agent.get_global_anchor()
            if anchor is not None:
                frame["anchor"] = np.asarray(anchor)
        bytes_sent += send_frame(conn, frame)
        merged = recv_frame(conn)  # bus barrier: everyone's round frames

        for peer in range(num_robots):
            if peer == robot_id:
                continue
            pf = {k.split("|", 1)[1]: v for k, v in merged.items()
                  if k.startswith(f"r{peer}|")}
            if not pf:
                continue
            ps = pf["status"]
            agent.set_neighbor_status(PGOAgentStatus(
                robot_id=int(ps[0]), state=AgentState(int(ps[1])),
                instance_number=int(ps[2]), iteration_number=int(ps[3]),
                ready_to_terminate=bool(ps[4]),
                relative_change=float(pf["relchange"])))
            agent.update_neighbor_poses(peer, unpack_pose_dict(pf, "pose"))
            if robust:
                wd = {}
                for k, v in pf.items():
                    if k.startswith("wt_"):
                        _, r1, p1, r2, p2 = k.split("_")
                        wd[((int(r1), int(p1)), (int(r2), int(p2)))] = \
                            float(v)
                if wd:
                    agent.update_shared_weights(wd)
            if robot_id != 0 and "anchor" in pf and peer == 0:
                agent.set_global_anchor(pf["anchor"])

        if mode == "sync":
            agent.iterate(do_optimization=True)
        else:
            time.sleep(1.0 / async_rate)

    if mode == "async":
        agent.end_optimization_loop()

    # Final anchor sync so all trajectories live in the same frame.
    if robot_id == 0:
        send_frame(conn, {"anchor": np.asarray(agent.get_global_anchor())})
    else:
        send_frame(conn, {})
    merged = recv_frame(conn)
    if robot_id != 0:
        agent.set_global_anchor(merged["r0|anchor"])
    conn.close()

    st = agent.get_status()
    np.savez(os.path.join(out_dir, f"robot{robot_id}.npz"),
             T=agent.trajectory_in_global_frame(),
             state=np.asarray(st.state.value),
             iterations=np.asarray(st.iteration_number),
             bytes_sent=np.asarray(bytes_sent))
    if run is not None:
        # Wire-level bytes (length-prefixed npz frames) — the real transport
        # cost, alongside the payload bytes the agent hooks counted.
        run.metric("tcp_bytes_sent", bytes_sent, "bytes", phase="report",
                   robot=robot_id, rounds=rounds, mode=mode)
        run.metric("agent_final_iterations", st.iteration_number, phase="report",
                   robot=robot_id)
        obs.end_run()


# ---------------------------------------------------------------------------
# Launcher: bind the bus, spawn robots, relay rounds, assemble, report
# ---------------------------------------------------------------------------

def serve_bus(srv: socket.socket, num_robots: int, total_rounds: int):
    """Accept one connection per robot and relay ``total_rounds`` rounds:
    collect one frame from every robot, then broadcast the union (keys
    namespaced ``r{id}|...``) to all — the pub/sub role the reference
    delegates to dpgo_ros."""
    conns: dict[int, socket.socket] = {}
    while len(conns) < num_robots:
        c, _ = srv.accept()
        c.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        hello = recv_frame(c)
        conns[int(hello["hello"])] = c
    for _ in range(total_rounds):
        merged = {}
        for rid in sorted(conns):
            frame = recv_frame(conns[rid])
            merged.update({f"r{rid}|{k}": v for k, v in frame.items()})
        # Serialize once, broadcast the same bytes — np.savez per robot
        # would be O(N^2) redundant CPU per round.
        buf = io.BytesIO()
        np.savez(buf, **merged)
        data = struct.pack("<Q", buf.getbuffer().nbytes) + buf.getvalue()
        for rid in sorted(conns):
            conns[rid].sendall(data)
    for c in conns.values():
        c.close()


def launch(args) -> int:
    import subprocess
    import threading

    out_dir = args.out_dir or tempfile.mkdtemp(prefix="dpgo_tcp_")
    os.makedirs(out_dir, exist_ok=True)
    port_file = os.path.join(out_dir, "port.txt")
    if os.path.exists(port_file):  # reused --out-dir: drop the stale one
        os.unlink(port_file)

    # Bind FIRST (port 0 = OS-assigned), then publish atomically — no
    # pick-then-rebind TOCTOU window for another process to steal it.
    srv = socket.create_server(("127.0.0.1", args.port))
    port = srv.getsockname()[1]
    tmp = port_file + ".tmp"
    with open(tmp, "w") as fh:
        fh.write(str(port))
    os.replace(tmp, port_file)

    # ylift round + solve rounds + final anchor round
    bus = threading.Thread(target=serve_bus,
                           args=(srv, args.robots, args.rounds + 2),
                           daemon=True)
    bus.start()

    # Robot processes always run on CPU unless told otherwise: N python
    # processes cannot share the single tunneled-TPU grant (they would
    # deadlock at backend init), and the per-agent problems are tiny.
    child_env = dict(os.environ,
                     DPGO_PLATFORM=os.environ.get("DPGO_PLATFORM", "cpu"))
    procs = [subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), args.dataset,
         "--robot", str(rid), "--robots", str(args.robots),
         "--port", str(port), "--rank", str(args.rank),
         "--rounds", str(args.rounds), "--mode", args.mode,
         "--async-rate", str(args.async_rate), "--out-dir", out_dir]
        + (["--robust"] if args.robust else [])
        + (["--telemetry"] if args.telemetry else []),
        env=child_env) for rid in range(args.robots)]
    try:
        rcs = [p.wait(timeout=900) for p in procs]
    finally:
        # A hung/killed robot must not orphan its siblings.
        for p in procs:
            if p.poll() is None:
                p.kill()
    srv.close()
    if any(rcs):
        print(f"robot processes failed: {rcs}", file=sys.stderr)
        return 1

    # Assemble the global trajectory and evaluate the SE(d) cost.
    setup_jax()
    from dpgo_tpu.ops import quadratic
    from dpgo_tpu.types import edge_set_from_measurements
    from dpgo_tpu.utils.g2o import read_g2o
    from dpgo_tpu.utils.partition import partition_contiguous
    import jax.numpy as jnp

    meas = read_g2o(args.dataset)
    part = partition_contiguous(meas, args.robots)
    outs = [np.load(os.path.join(out_dir, f"robot{r}.npz"))
            for r in range(args.robots)]
    d = meas.d
    T = np.zeros((meas.num_poses, d, d + 1))
    for r, o in enumerate(outs):
        ids = part.global_index[r][part.global_index[r] >= 0]
        T[ids] = o["T"]
    edges_g = edge_set_from_measurements(part.meas_global)
    X = jnp.asarray(T)
    cost = float(quadratic.cost(X, edges_g))
    result = {
        "cost": cost,
        "states": [int(o["state"]) for o in outs],
        "iterations": [int(o["iterations"]) for o in outs],
        "bytes_sent": [int(o["bytes_sent"]) for o in outs],
        "out_dir": out_dir,
    }
    print(json.dumps(result))
    if args.telemetry:
        from dpgo_tpu.obs.report import render_report
        tdir = os.path.join(out_dir, "telemetry")
        for rid in range(args.robots):
            rd = os.path.join(tdir, f"robot{rid}")
            if os.path.isdir(rd):
                print(file=sys.stderr)
                print(render_report(rd), file=sys.stderr)
        print(f"\nPer-robot telemetry under {tdir} — re-render with: "
              f"python -m dpgo_tpu.obs.report {tdir}/robot<id>",
              file=sys.stderr)
    return 0


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("dataset")
    ap.add_argument("--robots", type=int, default=2)
    ap.add_argument("--rank", type=int, default=5)
    ap.add_argument("--rounds", type=int, default=120)
    ap.add_argument("--mode", choices=("sync", "async"), default="sync")
    ap.add_argument("--robust", action="store_true")
    ap.add_argument("--telemetry", action="store_true",
                    help="per-robot telemetry runs (dpgo_tpu.obs) under "
                         "OUT_DIR/telemetry/robot<id>, reported after the "
                         "solve")
    ap.add_argument("--async-rate", type=float, default=20.0,
                    help="async mode: per-robot Poisson iterate rate (Hz) "
                         "and the bus exchange cadence")
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--out-dir", default=None)
    ap.add_argument("--robot", type=int, default=None,
                    help="internal: run as this robot instead of launching")
    args = ap.parse_args()
    if args.robot is None:
        sys.exit(launch(args))
    run_robot(args.robot, args.dataset, args.robots, args.rank, args.rounds,
              args.port, args.out_dir, args.mode, args.robust,
              args.async_rate, telemetry=args.telemetry)


if __name__ == "__main__":
    main()
