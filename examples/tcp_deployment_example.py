#!/usr/bin/env python
"""N-process TCP deployment demo on the fault-tolerant comms subsystem.

Each robot is its own OS process holding one ``PGOAgent``; the deployment
message set — packed public-pose sets / ``update_neighbor_poses_packed``,
status gossip, GNC weight publication, lifting-matrix and global-anchor
broadcast — travels over localhost TCP as length-prefixed packed v2
frames (``--wire v1`` keeps the npz fallback for old peers;
``--wire-dtype bf16`` halves the pose payload).  The launcher doubles as
the message bus (the pub/sub role dpgo_ros plays in the reference's
deployments).  ``--staleness 1`` overlaps each robot's RTR step with its
round's exchange (bounded staleness, the RA-L 2020 async model); the
default 0 keeps the deterministic lockstep schedule.

Unlike the original ad-hoc wire code, everything here rides
``dpgo_tpu.comms``: per-message deadlines, bounded retry with backoff,
sequence numbers (stale/reordered pose frames are dropped, never applied),
heartbeat liveness, and graceful degradation — a robot that dies mid-solve
is detected by the bus (closed transport or heartbeat silence), announced
to the survivors via the ``_lost`` broadcast key, excluded from the
``should_terminate`` quorum (``PGOAgent.mark_neighbor_lost``), and the
remaining team finishes.  Its last published poses stay frozen in every
survivor's neighbor cache (the RA-L 2020 delay-tolerance model).

Modes:

* ``--mode sync`` (default): each robot takes one ``iterate()`` per bus
  round.  With no faults injected the schedule is deterministic (the bus
  waits ``--round-timeout`` for every live robot, so lockstep is
  preserved).
* ``--mode async``: each robot runs its Poisson-clock optimization thread
  (``start_optimization_loop``) while the main thread exchanges poses at
  the bus cadence — iteration and communication fully decoupled.

Fault injection (seeded, deterministic per link) for chaos demos:
    python examples/tcp_deployment_example.py DATA.g2o --robots 3 \
        --rounds 60 --fault-drop 0.1 --fault-delay 0.2 \
        --fault-delay-s 0.05 0.2 --fault-seed 7 --round-timeout 2 \
        --kill-robot 2 --kill-round 40

Usage (launcher spawns all robot processes and assembles the result):
    python examples/tcp_deployment_example.py DATASET.g2o \
        [--robots 2] [--rank 5] [--rounds 120] [--mode sync|async] \
        [--robust] [--port 0] [--out-dir DIR] [--telemetry]

Internal per-robot entry (what the launcher spawns; the launcher binds the
listener FIRST and passes the resolved port down — there is no ephemeral-
port race and no port file):
    ... --robot ID --port P
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from _common import setup_jax  # noqa: E402


def make_injector(args, seed_offset: int):
    """Build the (initially disabled) per-process fault injector, or None
    when no fault flag is set.  The lifting-matrix broadcast and the final
    anchor sync always run clean; faults cover only solve rounds."""
    from dpgo_tpu.comms import FaultInjector, FaultSpec

    spec = FaultSpec(drop=args.fault_drop, delay=args.fault_delay,
                     delay_s=tuple(args.fault_delay_s),
                     reorder=args.fault_reorder, corrupt=args.fault_corrupt)
    if not spec.any_active():
        return None
    inj = FaultInjector(spec, seed=args.fault_seed + seed_offset)
    inj.enabled = False
    return inj


# ---------------------------------------------------------------------------
# One robot process
# ---------------------------------------------------------------------------

def run_robot(args) -> None:
    setup_jax()
    from dpgo_tpu import obs
    from dpgo_tpu.agent import PGOAgent
    from dpgo_tpu.comms import (BusClient, ReliableChannel, RetryPolicy,
                                TcpTransport, TransportClosed,
                                apply_peer_frame, connect_tcp,
                                pack_agent_frame)
    from dpgo_tpu.config import AgentParams, RobustCostParams, RobustCostType
    from dpgo_tpu.utils.g2o import read_g2o
    from dpgo_tpu.utils.partition import agent_measurements, \
        partition_contiguous

    rid, rounds, mode, robust = args.robot, args.rounds, args.mode, args.robust
    out_dir = args.out_dir

    # Each robot process scopes its own telemetry run (one run dir per
    # robot, like the reference's one-logDirectory-per-process layout).
    run = obs.start_run(
        os.path.join(out_dir, "telemetry", f"robot{rid}")) \
        if args.telemetry else None
    if run is not None:
        run.set_fingerprint(dataset=args.dataset, num_robots=args.robots,
                            rank=args.rank, robust=robust)

    meas = read_g2o(args.dataset)
    rp = RobustCostParams(cost_type=RobustCostType.GNC_TLS) if robust \
        else RobustCostParams()
    params = AgentParams(d=meas.d, r=args.rank, num_robots=args.robots,
                         robust=rp)
    part = partition_contiguous(meas, args.robots)
    agent = PGOAgent(rid, params)

    injector = make_injector(args, seed_offset=rid)
    sock = connect_tcp("127.0.0.1", args.port)
    wire_v2 = args.wire == "v2"
    transport = TcpTransport(sock, src=f"robot{rid}", dst="bus",
                             injector=injector,
                             wire_format="packed" if wire_v2 else "npz")
    policy = RetryPolicy(send_timeout_s=args.round_timeout,
                         recv_timeout_s=args.round_timeout)
    client = BusClient(ReliableChannel(transport, f"robot{rid}->bus",
                                       policy), rid)
    client.hello(timeout=30.0)
    client.channel.start_heartbeat(args.heartbeat_s)

    # Lifting-matrix broadcast (robot 0 self-generates; reference
    # MultiRobotExample.cpp:139-146) — rides the first bus round, clean.
    first = {"ylift": agent.get_lifting_matrix()} if rid == 0 else {}
    merged = client.exchange(first, timeout=60.0)
    for _ in range(3):
        if rid == 0 or (merged is not None and "r0|ylift" in merged):
            break
        merged = client.collect(timeout=60.0)
    if rid != 0:
        if merged is None or "r0|ylift" not in merged:
            raise ConnectionError(f"robot {rid}: lifting matrix never "
                                  "arrived")
        agent.set_lifting_matrix(merged["r0|ylift"])
    agent.set_pose_graph(*agent_measurements(part, rid))

    if mode == "async":
        agent.start_optimization_loop(rate_hz=args.async_rate)

    if injector is not None:
        injector.enabled = True
    # Compute/comm overlap: with --staleness >= 1 a background thread
    # publishes round k's poses and prefetches the broadcast while round
    # k's RTR step runs (bounded staleness, the RA-L 2020 async model);
    # --staleness 0 keeps the deterministic lockstep schedule.
    if args.staleness > 0:
        client.start_overlap(args.staleness, timeout=args.round_timeout)
    bus_gone = False
    for it in range(rounds):
        if args.die_at_round is not None and it == args.die_at_round:
            # Simulated mid-solve crash: drop the connection, write no
            # result.  The bus detects the closed transport, announces us
            # in `_lost`, and the survivors finish without us.
            if mode == "async":
                agent.end_optimization_loop()
            client.close()
            return
        frame = pack_agent_frame(agent, robust=robust,
                                 include_anchor=(rid == 0),
                                 wire_dtype=args.wire_dtype,
                                 packed=wire_v2)
        try:
            merged = client.exchange(frame, timeout=args.round_timeout)
        except TransportClosed:
            bus_gone = True  # keep the local result; stop exchanging
            break
        if merged is not None:
            for peer, pf in client.peer_frames(merged).items():
                apply_peer_frame(agent, peer, pf, robust=robust,
                                 accept_anchor=(rid != 0 and peer == 0))
            for lost in client.lost:
                agent.mark_neighbor_lost(lost)
        if mode == "sync":
            agent.iterate(do_optimization=True)
        else:
            time.sleep(1.0 / args.async_rate)
    try:
        client.drain_overlap(timeout=60.0)
    except TransportClosed:
        bus_gone = True
    client.stop_overlap()
    if injector is not None:
        injector.enabled = False

    if mode == "async":
        agent.end_optimization_loop()

    # Final anchor sync (clean) so all trajectories share one frame; a
    # survivor of a dead robot 0 falls back to the last anchor it cached.
    if not bus_gone:
        try:
            final = {"anchor": np.asarray(agent.get_global_anchor())} \
                if rid == 0 else {}
            merged = client.exchange(final, timeout=60.0)
            if rid != 0 and merged is not None and "r0|anchor" in merged:
                agent.set_global_anchor(merged["r0|anchor"])
        except TransportClosed:
            pass
    client.close()  # emits the comms run_summary into the ambient run

    st = agent.get_status()
    np.savez(os.path.join(out_dir, f"robot{rid}.npz"),
             T=agent.trajectory_in_global_frame(),
             state=np.asarray(st.state.value),
             iterations=np.asarray(st.iteration_number),
             bytes_sent=np.asarray(client.channel.totals.bytes_sent),
             lost=np.asarray(sorted(client.lost), np.int64))
    if run is not None:
        t = client.channel.totals
        run.metric("tcp_bytes_sent", t.bytes_sent, "bytes", phase="report",
                   robot=rid, rounds=rounds, mode=mode)
        run.metric("agent_final_iterations", st.iteration_number,
                   phase="report", robot=rid)
        obs.end_run()


# ---------------------------------------------------------------------------
# Launcher: bind the bus, spawn robots, relay rounds, assemble, report
# ---------------------------------------------------------------------------

def launch(args) -> int:
    import subprocess
    import threading

    from dpgo_tpu import obs
    from dpgo_tpu.comms import RetryPolicy, RoundBus, listen_tcp
    from dpgo_tpu.comms.bus import accept_robots

    out_dir = args.out_dir or tempfile.mkdtemp(prefix="dpgo_tcp_")
    os.makedirs(out_dir, exist_ok=True)

    # Bind FIRST (port 0 = OS-assigned), then pass the RESOLVED port down
    # on each robot's command line — no ephemeral-port race, no port file.
    srv = listen_tcp(port=args.port)
    port = srv.getsockname()[1]

    run = obs.start_run(os.path.join(out_dir, "telemetry", "bus")) \
        if args.telemetry else None

    # Robot processes always run on CPU unless told otherwise: N python
    # processes cannot share the single tunneled-TPU grant (they would
    # deadlock at backend init), and the per-agent problems are tiny.
    child_env = dict(os.environ,
                     DPGO_PLATFORM=os.environ.get("DPGO_PLATFORM", "cpu"))
    procs = []
    for rid in range(args.robots):
        cmd = [sys.executable, os.path.abspath(__file__), args.dataset,
               "--robot", str(rid), "--robots", str(args.robots),
               "--port", str(port), "--rank", str(args.rank),
               "--rounds", str(args.rounds), "--mode", args.mode,
               "--async-rate", str(args.async_rate), "--out-dir", out_dir,
               "--round-timeout", str(args.round_timeout),
               "--heartbeat-s", str(args.heartbeat_s),
               "--staleness", str(args.staleness),
               "--wire", args.wire, "--wire-dtype", args.wire_dtype,
               "--fault-drop", str(args.fault_drop),
               "--fault-delay", str(args.fault_delay),
               "--fault-delay-s", str(args.fault_delay_s[0]),
               str(args.fault_delay_s[1]),
               "--fault-reorder", str(args.fault_reorder),
               "--fault-corrupt", str(args.fault_corrupt),
               "--fault-seed", str(args.fault_seed)]
        if args.robust:
            cmd.append("--robust")
        if args.telemetry:
            cmd.append("--telemetry")
        if args.kill_robot is not None and rid == args.kill_robot:
            cmd += ["--die-at-round", str(args.kill_round)]
        procs.append(subprocess.Popen(cmd, env=child_env))

    injector = make_injector(args, seed_offset=1000)
    channels = accept_robots(
        srv, args.robots, injector=injector,
        policy=RetryPolicy(send_timeout_s=args.round_timeout,
                           recv_timeout_s=args.round_timeout),
        wire_format="packed" if args.wire == "v2" else "npz")
    bus = RoundBus(channels, round_timeout_s=args.round_timeout,
                   miss_limit=3,
                   liveness_timeout_s=max(1.0, 8 * args.heartbeat_s))

    def serve():
        bus.round()                     # lifting-matrix round (clean)
        if injector is not None:
            injector.enabled = True
        bus.serve(args.rounds)          # solve rounds (faults live)
        if injector is not None:
            injector.enabled = False
        bus.round()                     # final anchor round (clean)
        bus.close()                     # aggregated comms run_summary

    bus_thread = threading.Thread(target=serve, daemon=True)
    bus_thread.start()

    try:
        rcs = [p.wait(timeout=900) for p in procs]
    finally:
        # A hung/killed robot must not orphan its siblings.
        for p in procs:
            if p.poll() is None:
                p.kill()
    bus_thread.join(timeout=60)
    srv.close()
    if run is not None:
        obs.end_run()
    if any(rcs):
        print(f"robot processes failed: {rcs}", file=sys.stderr)
        return 1

    # Assemble the global trajectory and evaluate the SE(d) cost over the
    # edges whose BOTH endpoints belong to surviving robots (a killed
    # robot's block never made it to disk).
    setup_jax()
    from dpgo_tpu.ops import quadratic
    from dpgo_tpu.types import edge_set_from_measurements
    from dpgo_tpu.utils.g2o import read_g2o
    from dpgo_tpu.utils.partition import partition_contiguous
    import jax.numpy as jnp

    meas = read_g2o(args.dataset)
    part = partition_contiguous(meas, args.robots)
    outs, survivors = {}, []
    for r in range(args.robots):
        path = os.path.join(out_dir, f"robot{r}.npz")
        if os.path.exists(path):
            outs[r] = np.load(path)
            survivors.append(r)
    d = meas.d
    T = np.zeros((meas.num_poses, d, d + 1))
    for r, o in outs.items():
        ids = part.global_index[r][part.global_index[r] >= 0]
        T[ids] = o["T"]
    # Robot ownership lives in the robot-local view (meas_global keeps
    # r1 == r2 == 0 by construction); the two share row order.
    pm = part.meas
    keep = np.isin(np.asarray(pm.r1), survivors) & \
        np.isin(np.asarray(pm.r2), survivors)
    edges_g = edge_set_from_measurements(part.meas_global.select(keep))
    cost = float(quadratic.cost(jnp.asarray(T), edges_g))
    result = {
        "cost": cost,
        "states": [int(outs[r]["state"]) if r in outs else None
                   for r in range(args.robots)],
        "iterations": [int(outs[r]["iterations"]) if r in outs else None
                       for r in range(args.robots)],
        "bytes_sent": [int(outs[r]["bytes_sent"]) if r in outs else None
                       for r in range(args.robots)],
        "lost": sorted(set(range(args.robots)) - set(survivors)),
        "out_dir": out_dir,
    }
    print(json.dumps(result))
    if args.telemetry:
        from dpgo_tpu.obs import timeline
        from dpgo_tpu.obs.report import render_report
        tdir = os.path.join(out_dir, "telemetry")
        run_dirs = []
        for sub in ["bus"] + [f"robot{r}" for r in range(args.robots)]:
            rd = os.path.join(tdir, sub)
            if os.path.isdir(rd):
                run_dirs.append(rd)
                print(file=sys.stderr)
                print(render_report(rd), file=sys.stderr)
        # The fleet timeline: every process wrote its own event stream on
        # its own clock; merge estimates the per-process clock offsets
        # (from the stamps riding heartbeats and traced frames, relayed
        # through the bus) and renders one Perfetto-loadable trace with
        # cross-robot flow arrows.
        try:
            tl = timeline.merge(run_dirs)
            trace_path = timeline.write_chrome_trace(
                os.path.join(out_dir, "trace.json"), tl)
            counts = timeline.validate_chrome_trace(trace_path)
            print(f"\nFleet timeline: {trace_path} "
                  f"({counts['spans']} spans, {counts['flows']} flow "
                  f"edges) — open in https://ui.perfetto.dev",
                  file=sys.stderr)
            for s in tl.offsets["streams"]:
                unc = ("?" if s["uncertainty_s"] is None
                       else f"±{s['uncertainty_s'] * 1e3:.2f}ms")
                print(f"  clock {os.path.basename(s['path'])}: "
                      f"offset {s['offset_s'] * 1e3:+.2f}ms {unc}",
                      file=sys.stderr)
        except ValueError as e:
            print(f"\nFleet timeline export failed: {e}", file=sys.stderr)
        print(f"\nPer-robot telemetry under {tdir} — re-render with: "
              f"python -m dpgo_tpu.obs.report {tdir}/robot<id>; re-merge "
              f"with: python -m dpgo_tpu.obs.timeline {tdir}/*",
              file=sys.stderr)
    return 0


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("dataset")
    ap.add_argument("--robots", type=int, default=2)
    ap.add_argument("--rank", type=int, default=5)
    ap.add_argument("--rounds", type=int, default=120)
    ap.add_argument("--mode", choices=("sync", "async"), default="sync")
    ap.add_argument("--robust", action="store_true")
    ap.add_argument("--telemetry", action="store_true",
                    help="telemetry runs (dpgo_tpu.obs) under "
                         "OUT_DIR/telemetry/{bus,robot<id>}, reported "
                         "after the solve")
    ap.add_argument("--async-rate", type=float, default=20.0,
                    help="async mode: per-robot Poisson iterate rate (Hz) "
                         "and the bus exchange cadence")
    ap.add_argument("--round-timeout", type=float, default=120.0,
                    help="per-message send/recv deadline (s).  The large "
                         "default preserves deterministic lockstep on "
                         "fault-free runs (first-iterate compiles take "
                         "seconds); chaos runs should drop it to ~2s")
    ap.add_argument("--heartbeat-s", type=float, default=0.25,
                    help="robot->bus heartbeat interval (liveness)")
    ap.add_argument("--staleness", type=int, default=0,
                    help="compute/comm overlap bound: >=1 double-buffers "
                         "the exchange (round k's step runs while round "
                         "k's poses are on the wire); 0 keeps the "
                         "deterministic lockstep schedule")
    ap.add_argument("--wire", choices=("v2", "v1"), default="v2",
                    help="wire format: v2 = packed columnar frames "
                         "(zero-copy decode), v1 = per-pose npz (old-peer "
                         "interop)")
    ap.add_argument("--wire-dtype", choices=("f64", "f32", "bf16"),
                    default="f64",
                    help="pose payload dtype on the wire (v2); bf16 "
                         "halves pose bytes vs f32 and accumulates in "
                         "f32 on receipt")
    ap.add_argument("--fault-drop", type=float, default=0.0)
    ap.add_argument("--fault-delay", type=float, default=0.0)
    ap.add_argument("--fault-delay-s", type=float, nargs=2,
                    default=[0.05, 0.2], metavar=("MIN", "MAX"))
    ap.add_argument("--fault-reorder", type=float, default=0.0)
    ap.add_argument("--fault-corrupt", type=float, default=0.0)
    ap.add_argument("--fault-seed", type=int, default=0)
    ap.add_argument("--kill-robot", type=int, default=None,
                    help="launcher: tell this robot to crash mid-solve")
    ap.add_argument("--kill-round", type=int, default=None,
                    help="round at which --kill-robot dies")
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--out-dir", default=None)
    ap.add_argument("--robot", type=int, default=None,
                    help="internal: run as this robot instead of launching")
    ap.add_argument("--die-at-round", type=int, default=None,
                    help="internal: simulate a crash at this round")
    args = ap.parse_args()
    if args.kill_robot is not None and args.kill_round is None:
        ap.error("--kill-robot requires --kill-round")
    if args.robot is None:
        sys.exit(launch(args))
    run_robot(args)


if __name__ == "__main__":
    main()
