#!/usr/bin/env python
"""Two-process TCP deployment demo: the message vocabulary over a real wire.

The reference defers multi-process transport to the external ``dpgo_ros``
wrapper (``/root/reference/README.md:40-42``); the in-repo demos (ours and
the reference's) drive agents in one process.  This example goes one step
further than the reference's in-repo story: each robot is its own OS
process holding one ``PGOAgent``, and the deployment message set —
``get_shared_pose_dict`` / ``update_neighbor_poses``, status gossip,
lifting-matrix and global-anchor broadcast — travels over a localhost TCP
socket as length-prefixed ``npz`` frames.  This proves the agent API's
payloads actually serialize: nothing in the vocabulary needs shared
memory.

Usage (launcher spawns both robot processes and assembles the result):
    python examples/tcp_deployment_example.py DATASET.g2o \
        [--rank 5] [--rounds 120] [--port 0] [--out-dir DIR]

Internal per-robot entry (what the launcher spawns):
    ... --robot {0,1} --port P
"""

from __future__ import annotations

import argparse
import io
import json
import os
import socket
import struct
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from _common import setup_jax  # noqa: E402


# ---------------------------------------------------------------------------
# Wire format: length-prefixed npz frames (arrays only — no pickle)
# ---------------------------------------------------------------------------

def send_frame(sock: socket.socket, arrays: dict) -> int:
    buf = io.BytesIO()
    np.savez(buf, **arrays)
    data = buf.getvalue()
    sock.sendall(struct.pack("<Q", len(data)) + data)
    return 8 + len(data)


def recv_frame(sock: socket.socket) -> dict:
    def recv_exact(k):
        chunks = []
        while k:
            c = sock.recv(k)
            if not c:
                raise ConnectionError("peer closed")
            chunks.append(c)
            k -= len(c)
        return b"".join(chunks)

    (length,) = struct.unpack("<Q", recv_exact(8))
    return dict(np.load(io.BytesIO(recv_exact(length))))


def pack_pose_dict(prefix: str, pose_dict: dict) -> dict:
    return {f"{prefix}_{r}_{p}": np.asarray(block)
            for (r, p), block in pose_dict.items()}


def unpack_pose_dict(frame: dict, prefix: str) -> dict:
    out = {}
    for key, arr in frame.items():
        if key.startswith(prefix + "_"):
            _, r, p = key.rsplit("_", 2)
            out[(int(r), int(p))] = arr
    return out


# ---------------------------------------------------------------------------
# One robot process
# ---------------------------------------------------------------------------

def run_robot(robot_id: int, dataset: str, rank: int, rounds: int,
              port: int, out_dir: str) -> None:
    setup_jax()
    from dpgo_tpu.agent import AgentState, PGOAgent, PGOAgentStatus
    from dpgo_tpu.config import AgentParams
    from dpgo_tpu.utils.g2o import read_g2o
    from dpgo_tpu.utils.partition import agent_measurements, \
        partition_contiguous

    meas = read_g2o(dataset)
    params = AgentParams(d=meas.d, r=rank, num_robots=2)
    part = partition_contiguous(meas, 2)
    agent = PGOAgent(robot_id, params)

    # Robot 0 listens, robot 1 dials (with retries while 0 boots).  With
    # port 0 robot 0 binds an OS-assigned port itself and publishes the
    # choice through out_dir — no separate pick-then-bind window for
    # another process to steal the port (TOCTOU).
    port_file = os.path.join(out_dir, "port.txt")
    if robot_id == 0:
        if os.path.exists(port_file):  # reused out_dir: drop the stale one
            os.unlink(port_file)
        srv = socket.create_server(("127.0.0.1", port))
        port = srv.getsockname()[1]
        tmp = port_file + ".tmp"
        with open(tmp, "w") as fh:  # atomic publish: no partial reads
            fh.write(str(port))
        os.replace(tmp, port_file)
        conn, _ = srv.accept()
    else:
        dial = port
        for attempt in range(100):
            if port == 0:
                # Re-read every attempt: a stale file from a previous run
                # may be consumed before this run's robot 0 republishes.
                try:
                    with open(port_file) as fh:
                        dial = int(fh.read())
                except (FileNotFoundError, ValueError):
                    time.sleep(0.1)
                    continue
            try:
                conn = socket.create_connection(("127.0.0.1", dial))
                break
            except ConnectionRefusedError:
                time.sleep(0.1)
        else:
            where = f"port {dial}" if dial else f"port file {port_file}"
            raise ConnectionError(
                f"robot 1 could not reach robot 0 ({where})")
    conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)

    # Lifting-matrix broadcast (robot 0 self-generates; reference
    # MultiRobotExample.cpp:139-146).
    if robot_id == 0:
        send_frame(conn, {"ylift": agent.get_lifting_matrix()})
    else:
        agent.set_lifting_matrix(recv_frame(conn)["ylift"])
    agent.set_pose_graph(*agent_measurements(part, robot_id))

    peer = 1 - robot_id
    bytes_sent = 0
    for it in range(rounds):
        st = agent.get_status()
        frame = {"status": np.asarray(
            [st.robot_id, st.state.value, st.instance_number,
             st.iteration_number, int(st.ready_to_terminate)], np.int64),
            "relchange": np.asarray(st.relative_change, np.float64)}
        frame.update(pack_pose_dict("pose", agent.get_shared_pose_dict()))
        if robot_id == 0:
            anchor = agent.get_global_anchor()
            if anchor is not None:
                frame["anchor"] = np.asarray(anchor)
        # Asymmetric order (0 sends first, 1 receives first): a symmetric
        # send-then-recv deadlocks once a pose frame outgrows the loopback
        # socket buffers (both peers blocked in sendall).
        if robot_id == 0:
            bytes_sent += send_frame(conn, frame)
            peer_frame = recv_frame(conn)
        else:
            peer_frame = recv_frame(conn)
            bytes_sent += send_frame(conn, frame)
        ps = peer_frame["status"]
        agent.set_neighbor_status(PGOAgentStatus(
            robot_id=int(ps[0]), state=AgentState(int(ps[1])),
            instance_number=int(ps[2]), iteration_number=int(ps[3]),
            ready_to_terminate=bool(ps[4]),
            relative_change=float(peer_frame["relchange"])))
        agent.update_neighbor_poses(peer, unpack_pose_dict(peer_frame,
                                                           "pose"))
        if robot_id == 1 and "anchor" in peer_frame:
            agent.set_global_anchor(peer_frame["anchor"])

        agent.iterate(do_optimization=True)

    # Final anchor sync so both trajectories live in the same frame.
    if robot_id == 0:
        send_frame(conn, {"anchor": np.asarray(agent.get_global_anchor())})
    else:
        agent.set_global_anchor(recv_frame(conn)["anchor"])
    conn.close()

    st = agent.get_status()
    np.savez(os.path.join(out_dir, f"robot{robot_id}.npz"),
             T=agent.trajectory_in_global_frame(),
             state=np.asarray(st.state.value),
             iterations=np.asarray(st.iteration_number),
             bytes_sent=np.asarray(bytes_sent))


# ---------------------------------------------------------------------------
# Launcher: spawn both robots, wait, assemble, report
# ---------------------------------------------------------------------------

def launch(args) -> int:
    import subprocess

    out_dir = args.out_dir or tempfile.mkdtemp(prefix="dpgo_tcp_")
    os.makedirs(out_dir, exist_ok=True)
    # port 0 flows through to robot 0, which binds it and publishes the
    # OS-assigned choice via out_dir/port.txt (read by robot 1) — binding
    # in the child avoids the pick-then-rebind TOCTOU window.
    port = args.port
    stale = os.path.join(out_dir, "port.txt")
    if os.path.exists(stale):  # reused --out-dir: drop the previous run's
        os.unlink(stale)

    # Robot processes always run on CPU unless told otherwise: two python
    # processes cannot share the single tunneled-TPU grant (they would
    # deadlock at backend init), and the per-agent problems are tiny.
    child_env = dict(os.environ,
                     DPGO_PLATFORM=os.environ.get("DPGO_PLATFORM", "cpu"))
    procs = [subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), args.dataset,
         "--robot", str(rid), "--port", str(port), "--rank", str(args.rank),
         "--rounds", str(args.rounds), "--out-dir", out_dir],
        env=child_env) for rid in (0, 1)]
    try:
        rcs = [p.wait(timeout=600) for p in procs]
    finally:
        # A hung/killed robot must not orphan its sibling.
        for p in procs:
            if p.poll() is None:
                p.kill()
    if any(rcs):
        print(f"robot processes failed: {rcs}", file=sys.stderr)
        return 1

    # Assemble the global trajectory and evaluate the SE(d) cost.
    setup_jax()
    from dpgo_tpu.ops import quadratic
    from dpgo_tpu.types import edge_set_from_measurements
    from dpgo_tpu.utils.g2o import read_g2o
    from dpgo_tpu.utils.partition import partition_contiguous
    import jax.numpy as jnp

    meas = read_g2o(args.dataset)
    part = partition_contiguous(meas, 2)
    outs = [np.load(os.path.join(out_dir, f"robot{r}.npz")) for r in (0, 1)]
    d = meas.d
    T = np.zeros((meas.num_poses, d, d + 1))
    for r, o in enumerate(outs):
        ids = part.global_index[r][part.global_index[r] >= 0]
        T[ids] = o["T"]
    edges_g = edge_set_from_measurements(part.meas_global)
    X = jnp.asarray(T)
    cost = float(quadratic.cost(X, edges_g))
    result = {
        "cost": cost,
        "states": [int(o["state"]) for o in outs],
        "iterations": [int(o["iterations"]) for o in outs],
        "bytes_sent": [int(o["bytes_sent"]) for o in outs],
        "out_dir": out_dir,
    }
    print(json.dumps(result))
    return 0


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("dataset")
    ap.add_argument("--rank", type=int, default=5)
    ap.add_argument("--rounds", type=int, default=120)
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--out-dir", default=None)
    ap.add_argument("--robot", type=int, default=None,
                    help="internal: run as this robot instead of launching")
    args = ap.parse_args()
    if args.robot is None:
        sys.exit(launch(args))
    run_robot(args.robot, args.dataset, args.rank, args.rounds, args.port,
              args.out_dir)


if __name__ == "__main__":
    main()
