#!/usr/bin/env python
"""Multi-robot RBCD on a key-partitioned C-SLAM dataset — the analog of the
reference's ``dpgo_compare`` (``examples/MultiRobotCSLAMComparison.cpp``):
robot assignments come from the gtsam-style symbol keys embedded in the g2o
file (high byte = robot character, decoded by ``key_to_robot_keyframe``,
reference ``DPGO_utils.cpp:21-33``) instead of a contiguous index split.

Usage:
    python examples/multi_robot_comparison.py DATASET.g2o [LOG_DIR]
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from _common import setup_jax  # noqa: E402


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("dataset", help="input .g2o file with key-encoded robot ids")
    ap.add_argument("log_dir", nargs="?", default=None)
    ap.add_argument("--rank", type=int, default=5)
    ap.add_argument("--max-iters", type=int, default=100)
    ap.add_argument("--grad-norm-tol", type=float, default=0.1)
    ap.add_argument("--robust", action="store_true")
    args = ap.parse_args()

    setup_jax()
    import jax.numpy as jnp
    import numpy as np

    from dpgo_tpu.config import AgentParams, RobustCostParams, RobustCostType
    from dpgo_tpu.models import rbcd
    from dpgo_tpu.utils import logger
    from dpgo_tpu.utils.g2o import read_g2o
    from dpgo_tpu.utils.partition import partition_by_keys

    meas = read_g2o(args.dataset)
    part = partition_by_keys(meas)
    print(f"Loaded {len(meas)} measurements, {part.num_robots} robots "
          f"(from keys), {part.meas_global.num_poses} poses (SE({meas.d}))")

    params = AgentParams(
        d=meas.d, r=args.rank, num_robots=part.num_robots, acceleration=True,
        robust=RobustCostParams(
            cost_type=RobustCostType.GNC_TLS if args.robust
            else RobustCostType.L2))

    t0 = time.perf_counter()
    result = rbcd.solve_rbcd(
        part.meas, part.num_robots, params=params, max_iters=args.max_iters,
        grad_norm_tol=args.grad_norm_tol, dtype=jnp.float64, part=part)
    dt = time.perf_counter() - t0

    for it, (f, gn) in enumerate(zip(result.cost_history,
                                     result.grad_norm_history)):
        print(f"iter {it + 1:4d}: cost {f:.6f}  gradnorm {gn:.6f}")
    print(f"Terminated by {result.terminated_by} after {result.iterations} "
          f"iterations in {dt:.2f}s")

    if args.log_dir:
        os.makedirs(args.log_dir, exist_ok=True)
        if meas.d == 3:
            logger.log_trajectory(
                np.asarray(result.T),
                os.path.join(args.log_dir, "trajectory_optimized.csv"))
        print(f"Logs written to {args.log_dir}")


if __name__ == "__main__":
    main()
