"""Shared setup for the example drivers."""

from __future__ import annotations

import os
import sys

# Make `dpgo_tpu` importable when an example runs as a script from anywhere.
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def setup_jax(force_x64_on_cpu: bool = True):
    """Pin the JAX platform and precision for an example run.

    The image's ``sitecustomize`` force-registers the TPU-tunnel platform and
    ignores the ``JAX_PLATFORMS`` env var, so ``DPGO_PLATFORM=cpu`` is honored
    here in code.  On a CPU-only backend float64 is enabled for tight numerics
    (on TPU the tunnel compiler requires the default f32/f64-off config).
    Returns the configured ``jax`` module.
    """
    import jax

    if os.environ.get("DPGO_PLATFORM"):
        jax.config.update("jax_platforms", os.environ["DPGO_PLATFORM"])
    if force_x64_on_cpu and all(d.platform == "cpu" for d in jax.devices()):
        jax.config.update("jax_enable_x64", True)
    return jax
