#!/usr/bin/env python
"""Centralized single-robot PGO — the analog of the reference's
``single-robot-example`` (``examples/SingleRobotExample.cpp``,
``PGOAgent::localPoseGraphOptimization``, ``PGOAgent.cpp:964-999``):
chordal initialization followed by an unrelaxed (r = d) Riemannian
trust-region solve of the whole dataset on one device.

Usage:
    python examples/single_robot_example.py DATASET.g2o [--rank R]
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from _common import setup_jax  # noqa: E402


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("dataset", help="input .g2o file")
    ap.add_argument("--rank", type=int, default=None,
                    help="relaxation rank (default d: no relaxation, as the "
                         "reference's local solve)")
    ap.add_argument("--max-iters", type=int, default=200)
    ap.add_argument("--grad-norm-tol", type=float, default=1e-1)
    ap.add_argument("--log-dir", default=None)
    args = ap.parse_args()

    setup_jax()
    import numpy as np

    from dpgo_tpu.config import SolverParams
    from dpgo_tpu.models.local_pgo import solve_local
    from dpgo_tpu.utils import logger
    from dpgo_tpu.utils.g2o import read_g2o

    meas = read_g2o(args.dataset)
    print(f"Loaded {len(meas)} measurements over {meas.num_poses} poses "
          f"(SE({meas.d})) from {args.dataset}")

    rank = args.rank or meas.d
    # Reference local-solve configuration (PGOAgent.cpp:979-987):
    # RTR, initial radius 10, gradnorm tol 1e-1, <=50 tCG iterations.
    params = SolverParams(initial_radius=10.0, grad_norm_tol=args.grad_norm_tol,
                          max_inner_iters=50, max_outer_iters=args.max_iters)

    t0 = time.perf_counter()
    res = solve_local(meas, rank=rank, params=params,
                      max_iters=args.max_iters,
                      grad_norm_tol=args.grad_norm_tol)
    dt = time.perf_counter() - t0
    print(f"Optimization complete: cost {res.cost:.6f}, "
          f"gradnorm {res.grad_norm:.3e}, {res.iters} RTR iterations "
          f"in {dt:.2f}s")

    if args.log_dir:
        os.makedirs(args.log_dir, exist_ok=True)
        if meas.d == 3:
            logger.log_trajectory(
                np.asarray(res.T),
                os.path.join(args.log_dir, "trajectory_optimized.csv"))
        print(f"Logs written to {args.log_dir}")


if __name__ == "__main__":
    main()
