#!/usr/bin/env python
"""Outlier-robust pipeline demo: corrupt a dataset's loop closures and
recover with iterated GNC.

The reference's GNC machinery (``src/DPGO_robust.cpp``,
``PGOAgent.cpp:1181-1245``) is exercised here at its actual job: a
chosen fraction of the loop closures is replaced with gross random
poses (``utils.synthetic.corrupt_loop_closures``, the GNC-paper
protocol), then the iterated robust solve
(``models.rbcd.solve_rbcd_robust_iterated``: anneal, hard-drop rejected
edges, re-anneal, reinstating any wrongly-dropped edge whose residual
recovers) rejects them.  Since this driver injected the corruption, it
can score the rejection — precision/recall against the ground truth and
the final cost on the true-inlier edge set (at benchmark scale:
recall 1.000 and cost within 1.6-6.3% of the outlier-free optimum at
10-40% corruption, BASELINE.md round-4 robustness table).

Usage:
    python examples/robust_corruption_example.py NUM_ROBOTS DATASET.g2o \
        [--fraction 0.2] [--rounds 3000]
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from _common import setup_jax  # noqa: E402


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("num_robots", type=int)
    ap.add_argument("dataset", help="input .g2o file")
    ap.add_argument("--fraction", type=float, default=0.2,
                    help="fraction of loop closures to corrupt")
    ap.add_argument("--rank", type=int, default=5)
    ap.add_argument("--rounds", type=int, default=3000,
                    help="max rounds per GNC pass (the reference's full "
                    "annealing is 100 weight updates x 30 rounds)")
    ap.add_argument("--passes", type=int, default=3)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--schedule", choices=["jacobi", "colored"],
                    default="colored")
    args = ap.parse_args()

    setup_jax()
    import jax.numpy as jnp
    import numpy as np

    from dpgo_tpu.config import (AgentParams, RobustCostParams,
                                 RobustCostType, Schedule)
    from dpgo_tpu.models import rbcd
    from dpgo_tpu.ops import quadratic
    from dpgo_tpu.types import edge_set_from_measurements
    from dpgo_tpu.utils.g2o import read_g2o
    from dpgo_tpu.utils.partition import (gather_poses_to_global,
                                          partition_contiguous)
    from dpgo_tpu.utils.synthetic import (corrupt_loop_closures,
                                          rejection_scores)

    clean = read_g2o(args.dataset)
    meas, outlier_idx = corrupt_loop_closures(clean, args.fraction,
                                              seed=args.seed)
    print(f"{clean.num_poses} poses, {len(clean)} edges; corrupted "
          f"{len(outlier_idx)} loop closures ({args.fraction:.0%})")

    params = AgentParams(
        d=clean.d, r=args.rank, num_robots=args.num_robots,
        schedule=Schedule(args.schedule),
        robust=RobustCostParams(cost_type=RobustCostType.GNC_TLS),
        rel_change_tol=0.0, acceleration=True, restart_interval=100)

    t0 = time.time()
    res, w, kept = rbcd.solve_rbcd_robust_iterated(
        meas, args.num_robots, params, passes=args.passes,
        max_iters=args.rounds, grad_norm_tol=0.0,
        eval_every=max(args.rounds // 4, 1))
    wall = time.time() - t0

    precision, recall, n_rej = rejection_scores(w, meas, outlier_idx)
    keep_true = np.ones(len(meas), bool)
    keep_true[outlier_idx] = False
    edges_in = edge_set_from_measurements(clean.select(keep_true))
    Xg = gather_poses_to_global(res.X,
                                partition_contiguous(meas, args.num_robots))
    f_in = float(quadratic.cost(jnp.asarray(Xg, jnp.float32),
                                edges_in))
    print(f"rejected {n_rej} edges (injected {len(outlier_idx)}): "
          f"precision {precision:.3f}, recall {recall:.3f}")
    print(f"cost on the true-inlier edges: {f_in:.3f} "
          f"({res.iterations} rounds across {args.passes} passes, "
          f"{wall:.1f}s)")


if __name__ == "__main__":
    main()
