#!/usr/bin/env python
"""Certifiably correct PGO via the Riemannian staircase — beyond-reference.

The reference implements the RBCD solver of the T-RO 2021 paper but not its
certification half (no certificate code exists in ``/root/reference/src``);
this driver exposes the framework's implementation (``dpgo_tpu.models.
certify``): solve the rank-r relaxation, test global optimality with the
dual-certificate minimum-eigenvalue solve, and climb the staircase
r -> r + 1 on failure until the solution is certified (BASELINE config #5
scope).

Usage:
    python examples/certification_example.py DATASET.g2o [--r-min R]
        [--r-max R] [--eta 1e-5] [--log-dir DIR]
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from _common import setup_jax  # noqa: E402


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("dataset", help="input .g2o file")
    ap.add_argument("--r-min", type=int, default=None,
                    help="starting relaxation rank (default d + 1)")
    ap.add_argument("--r-max", type=int, default=10)
    ap.add_argument("--eta", type=float, default=1e-5,
                    help="certificate tolerance on lambda_min(S)")
    ap.add_argument("--max-iters", type=int, default=300)
    ap.add_argument("--grad-norm-tol", type=float, default=1e-6)
    ap.add_argument("--log-dir", default=None)
    ap.add_argument("--distributed", type=int, default=0, metavar="A",
                    help="re-verify the final certificate decentralized: "
                         "partition over A agents and run the distributed "
                         "block LOBPCG over the device mesh "
                         "(dpgo_tpu.parallel.certify)")
    args = ap.parse_args()

    setup_jax()

    from dpgo_tpu.models.certify import solve_staircase
    from dpgo_tpu.utils import logger
    from dpgo_tpu.utils.g2o import read_g2o

    meas = read_g2o(args.dataset)
    print(f"Loaded {len(meas)} measurements over {meas.num_poses} poses "
          f"(SE({meas.d})) from {args.dataset}")

    t0 = time.perf_counter()
    res = solve_staircase(meas, r_min=args.r_min, r_max=args.r_max,
                          eta=args.eta, max_iters=args.max_iters,
                          grad_norm_tol=args.grad_norm_tol, verbose=True)
    dt = time.perf_counter() - t0

    cert = res.certificate
    print(f"Staircase finished at rank {res.rank} in {dt:.2f}s: "
          f"cost {res.cost:.6f}, lambda_min {cert.lambda_min:.3e}, "
          f"certified={cert.certified}")
    for rank, cost, lam in res.history:
        print(f"  rank {rank}: cost {cost:.6f}, lambda_min {lam:.3e}")
    if cert.certified:
        print("The rounded trajectory is a certified global optimum of the "
              "(weighted) PGO problem.")
    else:
        print(f"NOT certified at r_max={args.r_max}; consider raising it.")

    if args.distributed:
        # Decentralized verification of the same certificate: no agent ever
        # holds the global problem (T-RO 2021's distributed protocol).
        import math

        import jax
        import jax.numpy as jnp

        from dpgo_tpu.models import rbcd
        from dpgo_tpu.parallel import certify as dcert
        from dpgo_tpu.parallel.sharded import make_mesh
        from dpgo_tpu.utils.partition import partition_contiguous

        A = args.distributed
        part = partition_contiguous(meas, A)
        graph, _ = rbcd.build_graph(part, res.X.shape[1],
                                    jnp.asarray(res.X).dtype)
        Xa = rbcd.scatter_to_agents(jnp.asarray(res.X), graph)
        # The agent axis must divide the mesh: use the largest compatible
        # device count, and judge against the same eta as the staircase.
        mesh = make_mesh(math.gcd(A, len(jax.devices())))
        cd = dcert.certify_sharded(Xa, graph, mesh=mesh, eta=args.eta)
        print(f"Distributed certificate over {A} agents "
              f"({mesh.devices.size} devices): "
              f"lambda_min {cd.lambda_min:.3e}, certified={cd.certified}")

    if args.log_dir:
        os.makedirs(args.log_dir, exist_ok=True)
        logger.log_trajectory(
            res.T, os.path.join(args.log_dir, "trajectory_optimized.csv"))
        print(f"Saved certified trajectory to {args.log_dir}")


if __name__ == "__main__":
    main()
