"""Fleet soak benchmark: QPS vs. replicas, chaos soak, cold-start split.

The scale-out acceptance measurement for ``dpgo_tpu.serve.fleet``.
Three arms, one FLEET metric record:

1. **QPS vs. replicas** — the same stream of session-tagged small solves
   through a 1-replica fleet and then a 2-replica fleet (optionally
   more), with a shared pre-warmed persistent AOT cache so compiles never
   pollute the throughput numbers.  Rendezvous hashing spreads sessions
   across replicas, whose batch windows and device dispatches overlap;
   ``scaling_1_to_2`` (QPS ratio) is the number CI gates (>= 1.7 by
   default, ``FLEET_MIN_SCALING``).

2. **Chaos soak** — concurrent long-running live sessions on an
   autoscaling fleet (min 2, max 3 replicas, queue-wait SLO pinned low
   so the burn trips): mid-soak one replica is hard-killed and the
   autoscaler brings up another.  Every session must complete (the
   killed replica's sessions resume from their boundary snapshots on
   their rehashed replicas): the gate is ``lost == 0`` with
   ``migrations >= 1`` and ``scale_ups >= 1``.

3. **Cold start** — one replica compiles a fingerprint and persists it
   (cold), a fresh replica on the same cache root then serves its first
   solve from disk: the warm run's ``serve_compile_seconds_total`` must
   be exactly 0 with ``disk_hits >= 1`` (XLA never ran), and the record
   carries the cold/warm first-solve split.

Usage::

    JAX_PLATFORMS=cpu python bench_fleet.py --requests 16 --sessions 6
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

# The fleet's own disk tier is the thing under test; keep jax's global
# compilation cache out of the measurement.
os.environ.setdefault("DPGO_TPU_COMPILATION_CACHE", "0")

import jax  # noqa: E402

jax.config.update("jax_enable_x64", True)

import numpy as np  # noqa: E402

from dpgo_tpu import obs  # noqa: E402
from dpgo_tpu.config import AgentParams  # noqa: E402
from dpgo_tpu.obs.events import metric_record  # noqa: E402
from dpgo_tpu.serve import (FleetRouter, ReplicaManager, SolveRequest,  # noqa: E402
                            SolveServer)
from dpgo_tpu.utils.synthetic import make_measurements  # noqa: E402


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def make_meas(n: int, seed: int = 0):
    meas, _ = make_measurements(np.random.default_rng(seed), n=n, d=3,
                                num_lc=8, rot_noise=0.01, trans_noise=0.01)
    return meas


#: Consensus unreachable + zero gradient tolerance: solves run their full
#: iteration budget, so soak solves stay in flight long enough to migrate.
PARAMS = AgentParams(d=3, r=5, num_robots=2, rel_change_tol=-1.0)


def req(meas, sid=None, iters=2, eval_every=2):
    return SolveRequest(meas=meas, num_robots=2, params=PARAMS,
                        max_iters=iters, grad_norm_tol=0.0,
                        eval_every=eval_every, session_id=sid)


def build_fleet(n, aot_root, sess_root=None, max_replicas=None,
                batch_window_s=0.08, max_batch=2, procs=False, **mgr_kw):
    if procs:
        from dpgo_tpu.serve.fleet.procs import ProcServer

        def make_server(rid):
            # A real OS process per replica: the packed-v2 TCP front-end
            # is the RPC surface, kill_replica is an actual SIGKILL.
            return ProcServer(replica_id=rid, max_batch=max_batch,
                              batch_window_s=batch_window_s,
                              aot_cache_dir=aot_root,
                              session_store=sess_root, session_every=1,
                              resume_sessions=sess_root is not None)
    else:
        def make_server(rid):
            return SolveServer(max_batch=max_batch,
                               batch_window_s=batch_window_s,
                               replica_id=rid, aot_cache_dir=aot_root,
                               session_store=sess_root, session_every=1,
                               resume_sessions=sess_root is not None)

    mgr = ReplicaManager(make_server, min_replicas=n,
                         max_replicas=max_replicas,
                         monitor_interval_s=0.1, **mgr_kw)
    return FleetRouter(mgr)


#: QPS-arm coalescing window: each heterogeneous request pays this once
#: on a lone replica; replicas pay it concurrently.
QPS_WINDOW_S = 0.2


def balanced_sids(count, n_replicas):
    """Session ids pre-balanced over the fleet's deterministic replica
    ids (r0..r{n-1}) with the router's own rendezvous hash, so the arm
    measures scale-out rather than hash variance on tiny streams."""
    from dpgo_tpu.serve.fleet.router import _hrw_weight

    rids = [f"r{i}" for i in range(n_replicas)]
    per = {rid: 0 for rid in rids}
    quota = -(-count // n_replicas)
    out, i = [], 0
    while len(out) < count:
        sid = f"q{i}"
        i += 1
        rid = max(rids, key=lambda r: _hrw_weight(f"s|{sid}", r))
        if per[rid] < quota:
            per[rid] += 1
            out.append(sid)
    return out


def arm_qps(meas, replica_counts, requests, aot_root,
            procs=False) -> list[dict]:
    """The same heterogeneous request stream through fleets of ascending
    size.

    Every request carries a unique batch key (distinct ``grad_norm_tol``;
    identical compiled programs), so none coalesce: each dispatch is a
    batch of one that first waits out the coalescing window — the
    latency gamble the serving plane takes on every non-full batch.  A
    lone replica pays that window serially per request; a fleet pays it
    concurrently across members, which is precisely the scale-out win
    this arm measures.  The shared pre-warmed AOT disk cache keeps XLA
    out of the timings."""
    t0 = time.perf_counter()
    with SolveServer(max_batch=2, batch_window_s=0.0,
                     aot_cache_dir=aot_root) as srv:
        srv.solve(req(meas), timeout=600)
    log(f"[qps] warmed AOT cache in {time.perf_counter() - t0:.2f}s")

    def hreq(sid, k):
        # Unique grad_norm_tol => unique batch key, same executables
        # (the runner's compile fingerprints don't include it).
        r = req(meas, sid=sid)
        r.grad_norm_tol = 1e-12 * (k + 1)
        return r

    arms = []
    for n in replica_counts:
        sids = balanced_sids(requests, n)
        # max_batch above the stream depth: the queue never looks full,
        # so the window applies to every dispatch (the lone-replica cost
        # being measured); max_batch is not the contended resource here.
        router = build_fleet(n, aot_root, batch_window_s=QPS_WINDOW_S,
                             max_batch=2 * requests, procs=procs)
        try:
            # One throwaway request per replica pays its executable disk
            # load before the clock starts.
            warm = [router.submit(req(meas, sid=f"w{i}"))
                    for i in range(2 * n)]
            for t in warm:
                t.result(timeout=600)
            t0 = time.perf_counter()
            tickets = [router.submit(hreq(sid, k))
                       for k, sid in enumerate(sids)]
            for t in tickets:
                t.result(timeout=600)
            wall = time.perf_counter() - t0
        finally:
            router.close()
        arms.append({"replicas": n, "qps": round(requests / wall, 4),
                     "wall_s": round(wall, 4), "requests": requests,
                     "window_s": QPS_WINDOW_S})
        log(f"[qps] {n} replica(s): {arms[-1]['qps']} problems/s")
    return arms


def arm_soak(meas, sessions, soak_iters, aot_root, procs=False) -> dict:
    """Concurrent live sessions with a mid-soak kill AND a mid-soak
    autoscale-up; zero sessions may be lost.  With ``procs=True`` the
    kill is an actual ``SIGKILL`` of a replica OS process and sessions
    migrate across process boundaries via the shared snapshot store.

    The whole arm runs inside its own telemetry scope with a
    fast-cadence ``ResourceSampler``, so the record carries the
    flat-memory soak gate (``obs.regress.soak_memory_gate``) alongside
    the lost/migration tallies — the "memory held flat over the soak"
    claim as data, not prose."""
    from dpgo_tpu.obs import fleetobs
    from dpgo_tpu.obs.regress import soak_memory_gate

    sess_root = tempfile.mkdtemp(prefix="fleet-sess-")
    soak_run = tempfile.mkdtemp(prefix="fleet-soak-run-")
    # queue_wait_slo_s=0 => every completed request reads as burning the
    # wait budget, so the autoscaler provably trips mid-soak.
    with obs.run_scope(soak_run):
        sampler = fleetobs.start_resource_sampler(interval_s=0.25,
                                                  replica="bench")
        router = build_fleet(2, aot_root, sess_root=sess_root,
                             max_replicas=3,
                             queue_wait_slo_s=0.0, scale_cooldown_s=0.5,
                             min_scale_observations=2, scale_window_s=60.0,
                             batch_window_s=0.02, max_batch=2, procs=procs)
        mgr = router.manager
        try:
            tickets = {f"soak-{i}": router.submit(
                req(meas, sid=f"soak-{i}", iters=soak_iters, eval_every=1))
                for i in range(sessions)}
            # Let solves get in flight AND leave at least one boundary
            # snapshot before the kill (out-of-process replicas pay a
            # child boot first, so poll the store instead of a fixed
            # sleep).
            deadline = time.monotonic() + (120.0 if procs else 1.5)
            while time.monotonic() < deadline:
                import glob as _glob
                if _glob.glob(os.path.join(sess_root, "*", "snap-*.npz")):
                    break
                time.sleep(0.25)
            time.sleep(1.5)
            victim = mgr.replicas()[0].replica_id
            mgr.kill_replica(victim)
            log(f"[soak] killed {victim} mid-soak")
            lost, done = [], 0
            for sid, t in tickets.items():
                try:
                    t.result(timeout=900)
                    done += 1
                except Exception as e:
                    log(f"[soak] LOST {sid}: {type(e).__name__}: {e}")
                    lost.append(sid)
            st = mgr.status()
            migrations = router.migrations
        finally:
            router.close()
            if sampler is not None:
                sampler.close()
    gate = soak_memory_gate(soak_run)
    out = {"sessions": sessions, "completed": done, "lost": len(lost),
           "lost_ids": lost, "killed": victim, "migrations": migrations,
           "scale_ups": st["scale_ups"], "respawns": st["respawns"],
           "replicas_end": len(st["pool"]),
           "rss_flat": not gate["regressions"],
           "rss_gate": {who: {k: s.get(k) for k in
                              ("samples", "head_median", "tail_median",
                               "bound", "skipped", "regressed")}
                        for who, s in gate["series"].items()}}
    log(f"[soak] {out}")
    return out


def arm_cold_start(meas) -> dict:
    """Cold compile+persist, then a fresh replica proves the disk path:
    first solve with serve_compile_seconds_total == 0."""
    aot_root = tempfile.mkdtemp(prefix="fleet-aot-")

    def one_solve(label):
        with obs.run_scope(tempfile.mkdtemp(prefix=f"fleet-{label}-")) as run:
            t0 = time.perf_counter()
            with SolveServer(max_batch=2, batch_window_s=0.0,
                             aot_cache_dir=aot_root) as srv:
                srv.solve(req(meas), timeout=600)
                disk = srv.cache.stats()["disk"]
            wall = time.perf_counter() - t0
            compile_s = sum(run.counter(
                "serve_compile_seconds_total",
                "wall-clock spent in XLA compiles of serving executables",
                unit="s").series().values())
            run.metric("serve_cold_start_seconds", wall, "s", phase="bench",
                       arm=label, compile_seconds_total=compile_s,
                       disk_hits=disk["disk_hits"], stores=disk["stores"])
        return wall, compile_s, disk

    cold_s, cold_compile, cold_disk = one_solve("cold")
    warm_s, warm_compile, warm_disk = one_solve("warm")
    out = {"cold_first_solve_s": round(cold_s, 3),
           "warm_first_solve_s": round(warm_s, 3),
           "cold_compile_seconds_total": round(cold_compile, 3),
           "compile_seconds_total": round(warm_compile, 6),
           "disk_hits": warm_disk["disk_hits"],
           "stores": cold_disk["stores"],
           "speedup": round(cold_s / max(warm_s, 1e-9), 2)}
    log(f"[cold] {out}")
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--n-poses", type=int, default=24)
    ap.add_argument("--requests", type=int, default=16,
                    help="stream length for the QPS arm")
    ap.add_argument("--replicas", type=int, nargs="+", default=[1, 2],
                    help="ascending replica counts for the QPS arm")
    ap.add_argument("--sessions", type=int, default=6,
                    help="concurrent live sessions in the chaos soak")
    ap.add_argument("--soak-iters", type=int, default=400,
                    help="iteration budget of each soak session")
    ap.add_argument("--skip-soak", action="store_true")
    ap.add_argument("--skip-cold", action="store_true")
    ap.add_argument("--procs", action="store_true",
                    help="out-of-process replicas: each one a child OS "
                         "process behind the packed-v2 TCP front-end; "
                         "the soak kill is a real SIGKILL")
    ap.add_argument("--out", default=None,
                    help="also write the record JSON here (the checked-in "
                         "FLEET_r*.json ledger rows)")
    args = ap.parse_args(argv)

    meas = make_meas(args.n_poses)
    aot_root = tempfile.mkdtemp(prefix="fleet-aot-")

    qps = arm_qps(meas, args.replicas, args.requests, aot_root,
                  procs=args.procs)
    soak = {"skipped": True} if args.skip_soak else \
        arm_soak(meas, args.sessions, args.soak_iters, aot_root,
                 procs=args.procs)
    cold = {"skipped": True} if args.skip_cold else arm_cold_start(meas)

    by_n = {a["replicas"]: a["qps"] for a in qps}
    scaling = round(by_n[2] / by_n[1], 3) if 1 in by_n and 2 in by_n \
        else None
    ok = (soak.get("skipped")
          or (soak["lost"] == 0 and soak.get("rss_flat", True))) \
        and (cold.get("skipped") or cold["compile_seconds_total"] == 0.0)
    rec = metric_record(
        "fleet_qps",
        by_n.get(max(by_n)),
        "problems/s",
        record="FLEET",
        ok=bool(ok),
        backend=jax.default_backend(),
        out_of_process=bool(args.procs),
        qps=qps,
        scaling_1_to_2=scaling,
        soak=soak,
        cold_start=cold,
    )
    print(json.dumps(rec), flush=True)
    if args.out:
        with open(args.out, "w") as fh:
            json.dump(rec, fh, indent=2)
            fh.write("\n")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
