// Native g2o dataset parser for dpgo_tpu.
//
// C++ equivalent of the reference's C++ reader (`read_g2o_file`,
// /root/reference/src/DPGO_utils.cpp:78-212) — re-designed, not translated:
// instead of a std::stringstream-per-line loop building per-edge objects, the
// file is slurped once and tokenized in place with strtod/strtoull, and the
// output is struct-of-arrays buffers that map 1:1 onto the numpy arrays of
// `dpgo_tpu.types.Measurements` (zero-copy handoff through ctypes).
//
// Precisions follow the reference's information-divergence-minimizing
// choices (DPGO_utils.cpp:139-143, 184-194):
//   SE(3): tau = 3 / tr(inv(I_t)),  kappa = 3 / (2 tr(inv(I_R)))
//   SE(2): tau = 2 / tr(inv(I_t)),  kappa = I33
// where I_t / I_R are the translation / rotation blocks of the edge's
// information matrix.  Multi-robot gtsam symbol keys are returned raw; the
// Python side decodes them vectorized (key_to_robot_keyframe).
//
// Build: make -C native   (produces libdpgo_native.so next to this file;
// the ctypes wrapper dpgo_tpu/utils/native_io.py also auto-builds it).

#include <cctype>
#include <cmath>
#include <exception>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <vector>

namespace {

struct Parsed {
  int32_t d = 0;  // 2 or 3 (0 until first edge seen)
  int64_t num_vertices = 0;
  std::vector<uint64_t> key1, key2;
  std::vector<double> R;  // [m*d*d] row-major per edge
  std::vector<double> t;  // [m*d]
  std::vector<double> kappa, tau;
};

// --- tiny dense linear algebra (closed forms; no Eigen dependency) ---------

inline double inv_trace_2x2(const double a[4]) {
  // trace of inverse of [[a0,a1],[a2,a3]]
  double det = a[0] * a[3] - a[1] * a[2];
  return (a[3] + a[0]) / det;
}

inline double inv_trace_3x3(const double a[9]) {
  // trace of inverse = trace(adj(A))/det(A); diagonal cofactors only.
  double c00 = a[4] * a[8] - a[5] * a[7];
  double c11 = a[0] * a[8] - a[2] * a[6];
  double c22 = a[0] * a[4] - a[1] * a[3];
  double det = a[0] * c00 - a[1] * (a[3] * a[8] - a[5] * a[6]) +
               a[2] * (a[3] * a[7] - a[4] * a[6]);
  return (c00 + c11 + c22) / det;
}

inline void quat_to_R(double qx, double qy, double qz, double qw, double* R) {
  double n = std::sqrt(qx * qx + qy * qy + qz * qz + qw * qw);
  qx /= n; qy /= n; qz /= n; qw /= n;
  R[0] = 1 - 2 * (qy * qy + qz * qz);
  R[1] = 2 * (qx * qy - qz * qw);
  R[2] = 2 * (qx * qz + qy * qw);
  R[3] = 2 * (qx * qy + qz * qw);
  R[4] = 1 - 2 * (qx * qx + qz * qz);
  R[5] = 2 * (qy * qz - qx * qw);
  R[6] = 2 * (qx * qz - qy * qw);
  R[7] = 2 * (qy * qz + qx * qw);
  R[8] = 1 - 2 * (qx * qx + qy * qy);
}

// --- tokenizer -------------------------------------------------------------

inline const char* skip_ws(const char* p, const char* end) {
  while (p < end && (*p == ' ' || *p == '\t' || *p == '\r')) ++p;
  return p;
}

// Both tokenizer helpers record failure (no characters consumed, or token
// running past the line) in *ok so truncated/malformed lines surface as a
// parse error instead of silently zero-filling fields.
inline const char* next_double(const char* p, const char* end, double* out,
                               bool* ok) {
  p = skip_ws(p, end);
  char* q;
  *out = strtod(p, &q);
  if (q == p || q > end) *ok = false;
  return q;
}

inline const char* next_u64(const char* p, const char* end, uint64_t* out,
                            bool* ok) {
  p = skip_ws(p, end);
  char* q;
  *out = strtoull(p, &q, 10);
  if (q == p || q > end) *ok = false;
  return q;
}

}  // namespace

extern "C" {

// Struct-of-arrays result; all buffers are malloc'd and owned by the struct
// until dpgo_g2o_free.
struct DpgoG2O {
  int32_t d;
  int64_t m;
  int64_t num_vertices;
  uint64_t* key1;
  uint64_t* key2;
  double* R;      // [m*d*d]
  double* t;      // [m*d]
  double* kappa;  // [m]
  double* tau;    // [m]
  char error[256];
};

static double* dup_vec(const std::vector<double>& v) {
  double* p = (double*)malloc(v.size() * sizeof(double));
  memcpy(p, v.data(), v.size() * sizeof(double));
  return p;
}

static uint64_t* dup_vec_u64(const std::vector<uint64_t>& v) {
  uint64_t* p = (uint64_t*)malloc(v.size() * sizeof(uint64_t));
  memcpy(p, v.data(), v.size() * sizeof(uint64_t));
  return p;
}

// Body of the reader; may throw (std::bad_alloc, std::length_error from
// vector growth) — the extern "C" entry point catches everything so no
// exception ever crosses the ctypes boundary.
struct FileCloser {
  FILE* f;
  ~FileCloser() { if (f) fclose(f); }
};

static int dpgo_g2o_read_impl(const char* path, DpgoG2O* out) {
  FileCloser fc{fopen(path, "rb")};
  FILE* f = fc.f;
  if (!f) {
    snprintf(out->error, sizeof(out->error), "cannot open %s", path);
    return 1;
  }
  fseek(f, 0, SEEK_END);
  long size = ftell(f);
  fseek(f, 0, SEEK_SET);
  // ftell is -1 on error and a bogus huge value for directories; any real
  // .g2o dataset is far below 16 GiB.
  if (size < 0 || size > (1L << 34)) {
    snprintf(out->error, sizeof(out->error), "cannot read %s (not a regular file?)", path);
    return 1;
  }
  std::vector<char> buf(size + 1);
  if (fread(buf.data(), 1, size, f) != (size_t)size) {
    snprintf(out->error, sizeof(out->error), "short read on %s", path);
    return 1;
  }
  buf[size] = '\0';

  Parsed ps;
  const char* p = buf.data();
  const char* end = buf.data() + size;

  while (p < end) {
    const char* nl = (const char*)memchr(p, '\n', end - p);
    const char* line_end = nl ? nl : end;
    p = skip_ws(p, line_end);
    if (p >= line_end) { p = line_end + 1; continue; }

    if (strncmp(p, "EDGE_SE3:QUAT", 13) == 0 &&
        (p[13] == ' ' || p[13] == '\t')) {
      if (ps.d == 2) {
        snprintf(out->error, sizeof(out->error),
                 "mixed SE2/SE3 edges in %s", path);
        return 2;
      }
      ps.d = 3;
      const char* q = p + 13;
      bool ok = true;
      uint64_t k1, k2;
      q = next_u64(q, line_end, &k1, &ok);
      q = next_u64(q, line_end, &k2, &ok);
      double v[7 + 21];
      for (int i = 0; i < 7 + 21; ++i) q = next_double(q, line_end, &v[i], &ok);
      if (!ok) {
        snprintf(out->error, sizeof(out->error),
                 "malformed EDGE_SE3:QUAT line (edge %zu)", ps.key1.size());
        return 2;
      }
      ps.key1.push_back(k1);
      ps.key2.push_back(k2);
      ps.t.insert(ps.t.end(), {v[0], v[1], v[2]});
      double R[9];
      quat_to_R(v[3], v[4], v[5], v[6], R);
      ps.R.insert(ps.R.end(), R, R + 9);
      // Upper-triangular 6x6 information, row-major tail:
      // I11 I12 I13 I14 I15 I16 I22 I23 ... (21 entries from v[7]).
      const double* I = v + 7;
      double It[9] = {I[0], I[1], I[2], I[1], I[6], I[7], I[2], I[7], I[11]};
      double Ir[9] = {I[15], I[16], I[17], I[16], I[18], I[19],
                      I[17], I[19], I[20]};
      ps.tau.push_back(3.0 / inv_trace_3x3(It));
      ps.kappa.push_back(3.0 / (2.0 * inv_trace_3x3(Ir)));
    } else if (strncmp(p, "EDGE_SE2", 8) == 0 &&
               (p[8] == ' ' || p[8] == '\t')) {
      if (ps.d == 3) {
        snprintf(out->error, sizeof(out->error),
                 "mixed SE2/SE3 edges in %s", path);
        return 2;
      }
      ps.d = 2;
      const char* q = p + 8;
      bool ok = true;
      uint64_t k1, k2;
      q = next_u64(q, line_end, &k1, &ok);
      q = next_u64(q, line_end, &k2, &ok);
      double v[3 + 6];
      for (int i = 0; i < 3 + 6; ++i) q = next_double(q, line_end, &v[i], &ok);
      if (!ok) {
        snprintf(out->error, sizeof(out->error),
                 "malformed EDGE_SE2 line (edge %zu)", ps.key1.size());
        return 2;
      }
      ps.key1.push_back(k1);
      ps.key2.push_back(k2);
      ps.t.insert(ps.t.end(), {v[0], v[1]});
      double c = std::cos(v[2]), s = std::sin(v[2]);
      ps.R.insert(ps.R.end(), {c, -s, s, c});
      // Info order: I11 I12 I13 I22 I23 I33 (v[3..8]).
      double It[4] = {v[3], v[4], v[4], v[6]};
      ps.tau.push_back(2.0 / inv_trace_2x2(It));
      ps.kappa.push_back(v[8]);  // I33
    } else if (strncmp(p, "VERTEX", 6) == 0) {
      ++ps.num_vertices;
    } else if (strncmp(p, "FIX", 3) == 0 &&
               (p + 3 >= line_end || isspace((unsigned char)p[3]))) {
      // Standard g2o gauge anchor (ais2klinik.g2o) — accepted and ignored;
      // the framework fixes gauge via the global anchor instead.
    } else {
      // Mirror the reference's hard failure on unknown tokens
      // (DPGO_utils.cpp:201-205) so silent format drift is caught.
      char tok[32] = {0};
      size_t n = 0;
      while (p + n < line_end && !isspace((unsigned char)p[n]) && n < 31) ++n;
      memcpy(tok, p, n);
      snprintf(out->error, sizeof(out->error), "unrecognized token '%s'", tok);
      return 2;
    }
    p = line_end + 1;
  }

  if (ps.key1.empty()) {
    snprintf(out->error, sizeof(out->error), "no edges found in %s", path);
    return 2;
  }

  out->d = ps.d;
  out->m = (int64_t)ps.key1.size();
  out->num_vertices = ps.num_vertices;
  out->key1 = dup_vec_u64(ps.key1);
  out->key2 = dup_vec_u64(ps.key2);
  out->R = dup_vec(ps.R);
  out->t = dup_vec(ps.t);
  out->kappa = dup_vec(ps.kappa);
  out->tau = dup_vec(ps.tau);
  return 0;
}

// Returns 0 on success; on failure returns nonzero with out->error set.
// Never throws: a C++ exception escaping the C ABI would terminate() the
// embedding (Python) process.
int dpgo_g2o_read(const char* path, DpgoG2O* out) {
  memset(out, 0, sizeof(*out));
  try {
    return dpgo_g2o_read_impl(path, out);
  } catch (const std::exception& e) {
    snprintf(out->error, sizeof(out->error), "native parser error: %s", e.what());
    return 3;
  } catch (...) {
    snprintf(out->error, sizeof(out->error), "native parser error (unknown)");
    return 3;
  }
}

void dpgo_g2o_free(DpgoG2O* out) {
  free(out->key1);
  free(out->key2);
  free(out->R);
  free(out->t);
  free(out->kappa);
  free(out->tau);
  memset(out, 0, sizeof(*out));
}

}  // extern "C"
