// Native multi-agent graph planner for dpgo_tpu.
//
// The reference ingests and classifies measurements in C++
// (PGOAgent::setPoseGraph + addOdometry/add*LoopClosure,
// src/PGOAgent.cpp:126-248, building index maps of public poses and
// neighbor references).  This is the equivalent host-runtime component for
// the batched TPU layout (models/rbcd.py build_graph): given the edge
// endpoints (robot, pose) it computes, per agent,
//   * the padded edge rows (i, j, measurement id) where remote endpoints
//     are redirected to neighbor slots  [A, e_max]
//   * the public-pose table (local poses touched by inter-robot edges)
//     [A, p_max]
//   * the neighbor-slot table (remote robot, remote public position)
//     [A, s_max]
//   * the ELL incidence of local poses over the [gi | gj] edge-gradient
//     concatenation  [A, n_max, k_max]
// mirroring the Python planner exactly (same insertion orders, so the two
// backends produce identical arrays).  Payload scatter (rotations,
// weights, one-hot selection matrices) stays in numpy — it is already
// vectorized there.
//
// Plain C ABI for ctypes.  The library allocates, the caller copies into
// numpy and calls dpgo_graph_free.

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <unordered_map>
#include <vector>

namespace {

struct PlanOut {
  int32_t A = 0;
  int32_t n_max = 0;
  int32_t e_max = 0;
  int32_t s_max = 0;
  int32_t p_max = 0;
  int32_t k_max = 0;
  // [A * e_max]
  int32_t* ei = nullptr;
  int32_t* ej = nullptr;
  int64_t* meas_id = nullptr;
  uint8_t* emask = nullptr;
  // [A * p_max]
  int64_t* pub_idx = nullptr;
  uint8_t* pub_mask = nullptr;
  // [A * s_max]
  int32_t* nbr_robot = nullptr;
  int32_t* nbr_pub = nullptr;
  uint8_t* nbr_mask = nullptr;
  // [A * n_max * k_max]
  int32_t* inc_slot = nullptr;
  uint8_t* inc_mask = nullptr;
  char error[256] = {0};
};

inline uint64_t pair_key(int32_t robot, int64_t pose) {
  // Poses are dataset indices (< 2^40 by a wide margin); robots < 2^16.
  return (static_cast<uint64_t>(static_cast<uint32_t>(robot)) << 40) ^
         static_cast<uint64_t>(pose);
}

template <typename T>
T* zalloc(size_t n) {
  return static_cast<T*>(std::calloc(n ? n : 1, sizeof(T)));
}

}  // namespace

extern "C" {

void dpgo_graph_free(PlanOut* out);

// Returns 0 on success, nonzero with out->error set otherwise.
int dpgo_graph_plan(int64_t M, const int32_t* r1, const int64_t* p1,
                    const int32_t* r2, const int64_t* p2, int32_t A,
                    int32_t n_max, PlanOut* out) {
  if (A <= 0 || n_max <= 0) {
    std::snprintf(out->error, sizeof(out->error),
                  "A (%d) and n_max (%d) must be positive", A, n_max);
    return 2;
  }
  out->A = A;
  out->n_max = n_max;

  // Pass 1: insertion-ordered public poses and neighbor slots per agent,
  // plus each agent's edge rows — the same scan order as the Python
  // planner so positions match exactly.
  std::vector<std::unordered_map<int64_t, int32_t>> pub(A);   // pose -> position
  std::vector<std::vector<int64_t>> pub_order(A);
  std::vector<std::unordered_map<uint64_t, int32_t>> nbr(A);  // (robot,pose) -> slot
  std::vector<std::vector<std::pair<int32_t, int64_t>>> nbr_order(A);
  struct Row {
    int64_t i, j, k;
  };
  std::vector<std::vector<Row>> rows(A);

  // First scan assigns public positions (both endpoints of each
  // inter-robot edge), mirroring the Python first loop.
  for (int64_t k = 0; k < M; ++k) {
    const int32_t a = r1[k], b = r2[k];
    if (a < 0 || a >= A || b < 0 || b >= A) {
      std::snprintf(out->error, sizeof(out->error),
                    "edge %lld references robot out of range [0, %d)",
                    static_cast<long long>(k), A);
      return 2;
    }
    if (a != b) {
      if (pub[a].emplace(p1[k], (int32_t)pub_order[a].size()).second)
        pub_order[a].push_back(p1[k]);
      if (pub[b].emplace(p2[k], (int32_t)pub_order[b].size()).second)
        pub_order[b].push_back(p2[k]);
    }
  }
  // Second scan assigns neighbor slots and builds edge rows.
  for (int64_t k = 0; k < M; ++k) {
    const int32_t a = r1[k], b = r2[k];
    const int64_t p = p1[k], q = p2[k];
    if (p < 0 || p >= n_max || q < 0 || q >= n_max) {
      std::snprintf(out->error, sizeof(out->error),
                    "edge %lld pose index out of range [0, %d)",
                    static_cast<long long>(k), n_max);
      return 2;
    }
    if (a == b) {
      rows[a].push_back({p, q, k});
    } else {
      auto ins_a = nbr[a].emplace(pair_key(b, q), (int32_t)nbr_order[a].size());
      if (ins_a.second) nbr_order[a].push_back({b, q});
      rows[a].push_back({p, n_max + ins_a.first->second, k});
      auto ins_b = nbr[b].emplace(pair_key(a, p), (int32_t)nbr_order[b].size());
      if (ins_b.second) nbr_order[b].push_back({a, p});
      rows[b].push_back({n_max + ins_b.first->second, q, k});
    }
  }

  int64_t e_max = 1, s_max = 1, p_max = 1;
  for (int32_t a = 0; a < A; ++a) {
    if ((int64_t)rows[a].size() > e_max) e_max = rows[a].size();
    if ((int64_t)nbr_order[a].size() > s_max) s_max = nbr_order[a].size();
    if ((int64_t)pub_order[a].size() > p_max) p_max = pub_order[a].size();
  }
  out->e_max = (int32_t)e_max;
  out->s_max = (int32_t)s_max;
  out->p_max = (int32_t)p_max;

  out->ei = zalloc<int32_t>(A * e_max);
  out->ej = zalloc<int32_t>(A * e_max);
  out->meas_id = zalloc<int64_t>(A * e_max);
  out->emask = zalloc<uint8_t>(A * e_max);
  out->pub_idx = zalloc<int64_t>(A * p_max);
  out->pub_mask = zalloc<uint8_t>(A * p_max);
  out->nbr_robot = zalloc<int32_t>(A * s_max);
  out->nbr_pub = zalloc<int32_t>(A * s_max);
  out->nbr_mask = zalloc<uint8_t>(A * s_max);
  if (!out->ei || !out->ej || !out->meas_id || !out->emask ||
      !out->pub_idx || !out->pub_mask || !out->nbr_robot || !out->nbr_pub ||
      !out->nbr_mask) {
    dpgo_graph_free(out);
    std::snprintf(out->error, sizeof(out->error), "out of memory");
    return 3;
  }

  // ELL incidence: count local-pose degrees over [gi | gj] slots.
  std::vector<std::vector<std::vector<int32_t>>> inc(A);
  int64_t k_max = 1;
  for (int32_t a = 0; a < A; ++a) {
    inc[a].assign(n_max, {});
    for (size_t idx = 0; idx < rows[a].size(); ++idx) {
      const Row& r = rows[a][idx];
      if (r.i < n_max) inc[a][r.i].push_back((int32_t)idx);
      if (r.j < n_max) inc[a][r.j].push_back((int32_t)(e_max + idx));
    }
    for (int32_t v = 0; v < n_max; ++v)
      if ((int64_t)inc[a][v].size() > k_max) k_max = inc[a][v].size();
  }
  out->k_max = (int32_t)k_max;
  out->inc_slot = zalloc<int32_t>((int64_t)A * n_max * k_max);
  out->inc_mask = zalloc<uint8_t>((int64_t)A * n_max * k_max);
  if (!out->inc_slot || !out->inc_mask) {
    dpgo_graph_free(out);
    std::snprintf(out->error, sizeof(out->error), "out of memory");
    return 3;
  }

  for (int32_t a = 0; a < A; ++a) {
    for (size_t idx = 0; idx < rows[a].size(); ++idx) {
      const Row& r = rows[a][idx];
      out->ei[a * e_max + idx] = (int32_t)r.i;
      out->ej[a * e_max + idx] = (int32_t)r.j;
      out->meas_id[a * e_max + idx] = r.k;
      out->emask[a * e_max + idx] = 1;
    }
    for (size_t pos = 0; pos < pub_order[a].size(); ++pos) {
      out->pub_idx[a * p_max + pos] = pub_order[a][pos];
      out->pub_mask[a * p_max + pos] = 1;
    }
    for (size_t slot = 0; slot < nbr_order[a].size(); ++slot) {
      out->nbr_robot[a * s_max + slot] = nbr_order[a][slot].first;
      const int32_t b = nbr_order[a][slot].first;
      out->nbr_pub[a * s_max + slot] =
          pub[b].at(nbr_order[a][slot].second);
      out->nbr_mask[a * s_max + slot] = 1;
    }
    for (int32_t v = 0; v < n_max; ++v) {
      const auto& lst = inc[a][v];
      for (size_t c = 0; c < lst.size(); ++c) {
        out->inc_slot[((int64_t)a * n_max + v) * k_max + c] = lst[c];
        out->inc_mask[((int64_t)a * n_max + v) * k_max + c] = 1;
      }
    }
  }
  return 0;
}

void dpgo_graph_free(PlanOut* out) {
  std::free(out->ei);
  std::free(out->ej);
  std::free(out->meas_id);
  std::free(out->emask);
  std::free(out->pub_idx);
  std::free(out->pub_mask);
  std::free(out->nbr_robot);
  std::free(out->nbr_pub);
  std::free(out->nbr_mask);
  std::free(out->inc_slot);
  std::free(out->inc_mask);
  *out = PlanOut{};
}

}  // extern "C"
