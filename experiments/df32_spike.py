"""Spike: do error-free f32 transforms survive XLA on this TPU?

Double-f32 (two-float) arithmetic needs two primitives to be EXACT:
  * two_sum(a, b)  -> (s, e) with a + b == s + e exactly (Knuth),
  * two_prod(a, b) -> (p, e) with a * b == p + e exactly (Dekker split).
Both break if the compiler reassociates, contracts a*b+c into fma with
different rounding, or flushes subnormals in the error terms.  This spike
measures the achieved precision of df32 add/mul/dot against numpy f64 on
the actual backend (TPU when present) — the go/no-go for the on-device
recenter (VERDICT r5 item 1).
"""
import sys

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, "/root/repo")


def two_sum(a, b):
    s = a + b
    bb = s - a
    e = (a - (s - bb)) + (b - bb)
    return s, e


_SPLIT = np.float32(4097.0)  # 2^12 + 1 for f32 (24-bit mantissa)


def split(a):
    c = _SPLIT * a
    hi = c - (c - a)
    return hi, a - hi


def two_prod(a, b):
    p = a * b
    ah, al = split(a)
    bh, bl = split(b)
    e = ((ah * bh - p) + ah * bl + al * bh) + al * bl
    return p, e


def df_add(xh, xl, yh, yl):
    s, e = two_sum(xh, yh)
    e = e + (xl + yl)
    return two_sum(s, e)


def df_mul(xh, xl, yh, yl):
    p, e = two_prod(xh, yh)
    e = e + (xh * yl + xl * yh)
    return two_sum(p, e)


def to_df(v64):
    hi = np.asarray(v64, np.float32)
    lo = np.asarray(v64 - hi.astype(np.float64), np.float32)
    return hi, lo


@jax.jit
def run(ah, al, bh, bl):
    sh, sl = df_add(ah, al, bh, bl)
    ph, pl = df_mul(ah, al, bh, bl)
    # dot product of 4096 terms via df accumulation (sequential fold)
    def body(i, c):
        ch, cl = c
        th, tl = df_mul(ah[i], al[i], bh[i], bl[i])
        return df_add(ch, cl, th, tl)
    dh, dl = jax.lax.fori_loop(0, ah.shape[0], body,
                               (jnp.float32(0), jnp.float32(0)))
    return sh, sl, ph, pl, dh, dl


def main():
    print("backend:", jax.default_backend(), jax.devices())
    rng = np.random.default_rng(0)
    n = 4096
    a64 = rng.standard_normal(n) * np.exp(rng.uniform(-8, 8, n))
    b64 = rng.standard_normal(n) * np.exp(rng.uniform(-8, 8, n))
    ah, al = to_df(a64)
    bh, bl = to_df(b64)
    sh, sl, ph, pl, dh, dl = [np.asarray(x, np.float64)
                              for x in run(*map(jnp.asarray, (ah, al, bh, bl)))]
    # reference in f64 on the df32-representable inputs
    a_r = ah.astype(np.float64) + al.astype(np.float64)
    b_r = bh.astype(np.float64) + bl.astype(np.float64)
    s_ref, p_ref = a_r + b_r, a_r * b_r
    d_ref = float(np.sum(a_r * b_r))
    rel = lambda got, ref: np.max(np.abs(got - ref) /
                                  np.maximum(np.abs(ref), 1e-300))
    print(f"add  max rel err: {rel(sh + sl, s_ref):.3e}")
    print(f"mul  max rel err: {rel(ph + pl, p_ref):.3e}")
    print(f"dot  rel err:     {abs((dh + dl - d_ref) / d_ref):.3e}")
    print(f"f32-only dot rel: "
          f"{abs((float(np.float32(np.sum(ah * bh))) - d_ref) / d_ref):.3e}")


if __name__ == "__main__":
    main()
