"""Find a small graph where JACOBI oscillates but COLORED descends."""
import os, sys
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)
import jax.numpy as jnp
import numpy as np
from dpgo_tpu.config import AgentParams, Schedule, SolverParams
from dpgo_tpu.models import rbcd
from dpgo_tpu.ops import manifold, quadratic
from dpgo_tpu.types import edge_set_from_measurements
from dpgo_tpu.utils.partition import partition_contiguous
sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tests"))
from synthetic import make_measurements


def run(tag, n, A, num_lc, noise, d, r, init, rounds=60, seed=3):
    rng = np.random.default_rng(seed)
    meas, _ = make_measurements(rng, n=n, d=d, num_lc=num_lc,
                                rot_noise=noise, trans_noise=noise)
    part = partition_contiguous(meas, A)
    edges_g = edge_set_from_measurements(part.meas_global, dtype=jnp.float64)
    out = {}
    for sched in (Schedule.JACOBI, Schedule.COLORED):
        params = AgentParams(d=d, r=r, num_robots=A, schedule=sched,
                             rel_change_tol=0.0,
                             solver=SolverParams(grad_norm_tol=1e-12,
                                                 max_inner_iters=10))
        graph, meta = rbcd.build_graph(part, r, jnp.float64)
        if init == "chordal":
            X0 = rbcd.centralized_chordal_init(part, meta, graph, jnp.float64)
        else:
            key = jax.random.PRNGKey(0)
            X0 = jax.vmap(manifold.project)(
                jax.random.normal(key, (A, meta.n_max, r, d + 1),
                                  jnp.float64))
        state = rbcd.init_state(graph, meta, X0, params=params)
        costs = []
        for it in range(rounds):
            state = rbcd.rbcd_step(state, graph, meta, params)
            f = float(quadratic.cost(
                rbcd.gather_to_global(state.X, graph, n), edges_g))
            costs.append(f)
        inc = sum(1 for a, b in zip(costs, costs[1:]) if b > a + 1e-9)
        out[sched.value] = (costs, inc, meta.num_colors)
    cj, ij, C = out["jacobi"]
    cc, ic, _ = out["colored"]
    print(f"{tag}: C={C} jacobi f_end={cj[-1]:.2f} inc={ij} | "
          f"colored f_end={cc[-1]:.2f} inc={ic}", flush=True)


run("A: hi-prec rand-init", 16, 8, 40, 0.01, 2, 3, "rand")
run("B: hi-prec chordal dense", 16, 8, 80, 0.005, 2, 3, "chordal")
run("C: 1-pose agents", 12, 12, 30, 0.01, 2, 3, "rand")
run("D: 3d rand", 16, 8, 40, 0.01, 3, 5, "rand")
