"""100k convergence check: bf16-select vs f32 cost trajectories."""
import os, sys, time
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import numpy as np

def main():
    import jax.numpy as jnp
    from dpgo_tpu.config import AgentParams, SolverParams
    from dpgo_tpu.models import rbcd
    from dpgo_tpu.ops import quadratic
    from dpgo_tpu.types import edge_set_from_measurements
    from dpgo_tpu.utils.partition import partition_contiguous
    from dpgo_tpu.utils.synthetic import make_measurements

    rng = np.random.default_rng(0)
    meas, _ = make_measurements(rng, n=100000, d=3, num_lc=20000,
                                rot_noise=0.01, trans_noise=0.01)
    part = partition_contiguous(meas, 64)
    edges_g = edge_set_from_measurements(part.meas_global, dtype=jnp.float32)
    n = meas.num_poses
    for bf16 in (False, True):
        params = AgentParams(d=3, r=5, num_robots=64, rel_change_tol=0.0,
                             solver=SolverParams(pallas_bf16_select=bf16))
        graph, meta = rbcd.build_graph(part, 5, jnp.float32)
        X0 = rbcd.centralized_chordal_init(part, meta, graph, jnp.float32)
        state = rbcd.init_state(graph, meta, X0, params=params)
        costs = []
        for _ in range(4):
            state = rbcd.rbcd_steps(state, graph, 25, meta, params)
            costs.append(float(quadratic.cost(
                rbcd.gather_to_global(state.X, graph, n), edges_g)))
        print(f"bf16={bf16}: costs@25/50/75/100 = "
              f"{['%.2f' % c for c in costs]}", flush=True)

main()
