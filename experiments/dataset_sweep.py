"""Sweep every benchmark dataset in the reference's data/ directory through
the distributed solver on the current backend (TPU when available).

For each dataset: partition into agents, chordal init, fused COLORED
RBCD rounds (the stable parallel schedule), report initial/final cost,
centralized Riemannian gradient norm, monotonicity of the eval trace, and
steady rounds/s.  One line per dataset; a markdown table at the end.

This is the breadth check the reference never had in-repo: its examples
run one dataset per invocation (``examples/MultiRobotExample.cpp``).
"""
from __future__ import annotations

import sys
import time

import numpy as np

sys.path.insert(0, "/root/repo")

DATA = "/root/reference/data"

# (file, agents, rank, rounds).  Agent counts follow BASELINE.json configs
# where one exists; smaller graphs get 4-8 agents.  Rank r=5 for 3D
# (BASELINE config #2), r=3 for 2D (config #4).
SWEEP = [
    ("tinyGrid3D.g2o", 2, 5, 100),
    ("smallGrid3D.g2o", 5, 5, 200),
    ("parking-garage.g2o", 8, 5, 200),
    ("sphere2500.g2o", 8, 5, 300),
    ("torus3D.g2o", 8, 5, 300),
    ("cubicle.g2o", 8, 5, 300),
    ("sphere_bignoise_vertex3.g2o", 8, 5, 300),
    ("CSAIL.g2o", 8, 3, 300),
    ("input_INTEL_g2o.g2o", 8, 3, 300),
    ("input_M3500_g2o.g2o", 16, 3, 300),
    ("input_MITb_g2o.g2o", 8, 3, 300),
    ("kitti_00.g2o", 16, 3, 300),
    ("kitti_02.g2o", 16, 3, 300),
    ("kitti_05.g2o", 16, 3, 300),
    ("kitti_06.g2o", 8, 3, 300),
    ("kitti_07.g2o", 8, 3, 300),
    ("kitti_08.g2o", 16, 3, 300),
    ("kitti_09.g2o", 8, 3, 300),
    ("city10000.g2o", 32, 3, 300),
    ("ais2klinik.g2o", 32, 3, 300),
]


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def one(fname, A, r, rounds):
    import jax
    import jax.numpy as jnp
    from dpgo_tpu.config import AgentParams, Schedule, SolverParams
    from dpgo_tpu.models import rbcd
    from dpgo_tpu.ops import manifold, quadratic
    from dpgo_tpu.types import edge_set_from_measurements
    from dpgo_tpu.utils.g2o import read_g2o
    from dpgo_tpu.utils.partition import partition_contiguous

    dtype = jnp.float32 if jax.devices()[0].platform != "cpu" \
        else jnp.float64
    meas = read_g2o(f"{DATA}/{fname}")
    params = AgentParams(d=meas.d, r=r, num_robots=A,
                         schedule=Schedule.COLORED, rel_change_tol=0.0,
                         solver=SolverParams(pallas_sel_mode="bf16x3"))
    part = partition_contiguous(meas, A)
    graph, meta = rbcd.build_graph(part, r, dtype)
    X0 = rbcd.centralized_chordal_init(part, meta, graph, dtype)
    state = rbcd.init_state(graph, meta, X0, params=params)
    edges_g = edge_set_from_measurements(part.meas_global, dtype=dtype)
    n_total = part.meas_global.num_poses

    @jax.jit
    def metrics(s):
        Xg = rbcd.gather_to_global(s.X, graph, n_total)
        f = quadratic.cost(Xg, edges_g)
        g = manifold.rgrad(Xg, quadratic.egrad(Xg, edges_g))
        return jnp.stack([f, manifold.norm(g)])

    form = rbcd._formulation(meta, params, graph,
                             itemsize=jnp.dtype(dtype).itemsize)
    f0, gn0 = np.asarray(metrics(state))
    # warm-up compile, then timed fused segments with a mid eval
    state = rbcd.rbcd_steps(state, graph, 1, meta, params)
    costs = [f0]
    f, gn = f0, gn0  # in case rounds <= 1 skips the eval loop entirely
    t0 = time.perf_counter()
    done = 1
    while done < rounds:
        k = min(rounds - done, max(1, rounds // 4))
        state = rbcd.rbcd_steps(state, graph, k, meta, params)
        done += k
        f, gn = np.asarray(metrics(state))
        costs.append(f)
    dt = time.perf_counter() - t0
    f1, gn1 = f, gn  # the loop's final eval is already at the last round
    inc = sum(1 for a, b in zip(costs, costs[1:]) if b > a * (1 + 1e-6))
    rate = (rounds - 1) / dt
    return dict(dataset=fname.replace("input_", "").replace("_g2o", ""),
                d=meas.d, n=meas.num_poses, m=len(meas), A=A, r=r,
                form=form, f0=float(f0), f1=float(f1), gn0=float(gn0),
                gn1=float(gn1), rounds=rounds, rate=rate, increases=inc)


def main():
    rows = []
    for fname, A, r, rounds in SWEEP:
        try:
            t0 = time.perf_counter()
            row = one(fname, A, r, rounds)
            row["wall"] = time.perf_counter() - t0
            rows.append(row)
            log(f"[{row['dataset']}] d={row['d']} n={row['n']} m={row['m']} "
                f"A={row['A']} form={row['form']} cost {row['f0']:.1f} -> "
                f"{row['f1']:.1f}, gradnorm {row['gn0']:.2f} -> "
                f"{row['gn1']:.3f}, {row['rate']:.0f} rounds/s, "
                f"increases={row['increases']}, wall {row['wall']:.0f}s")
        except Exception as e:  # noqa: BLE001 — keep sweeping
            log(f"[{fname}] FAILED: {type(e).__name__}: {e}")
            rows.append(dict(dataset=fname, error=str(e)))

    print("| dataset | d | poses | edges | agents | form | cost init -> final"
          " | gradnorm init -> final | rounds/s | monotone |")
    print("|---|---|---|---|---|---|---|---|---|---|")
    for row in rows:
        if "error" in row:
            print(f"| {row['dataset']} | FAILED: {row['error'][:60]} |")
            continue
        print(f"| {row['dataset']} | {row['d']} | {row['n']} | {row['m']} "
              f"| {row['A']} | {row['form']} "
              f"| {row['f0']:.1f} -> {row['f1']:.1f} "
              f"| {row['gn0']:.1f} -> {row['gn1']:.3f} "
              f"| {row['rate']:.0f} | "
              f"{'yes' if row['increases'] == 0 else 'NO (%d)' % row['increases']} |")


if __name__ == "__main__":
    main()
