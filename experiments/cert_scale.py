"""Distributed certification at scale on the TPU (VERDICT r2 item 6).

Runs the sharded dual certificate (parallel.certify.certify_sharded — the
same shard_map program the 8-device CPU mesh validates; here the mesh is
the single v5e chip) on city10000/32 and the 100k synthetic/64 after a
solver run, recording lambda_min, the stationarity gap, and wall-clock.
Probe counts are printed from the configuration (matvec count =
power_iters + sub_iters * (3 probes + rayleigh) ... reported explicitly).

Usage: python experiments/cert_scale.py [city 100k]
"""
from __future__ import annotations

import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def cpu_reference_cert(xg_path: str, meas_kind: str):
    """Centralized f64 certificate of a saved global iterate (CPU
    subprocess — cross-validates the sharded f32 result)."""
    import subprocess

    code = f"""
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)
import sys, numpy as np
sys.path.insert(0, "/root/repo")
import jax.numpy as jnp
from dpgo_tpu.models import certify
from dpgo_tpu.types import edge_set_from_measurements
if "{meas_kind}" == "city":
    from dpgo_tpu.utils.g2o import read_g2o
    meas = read_g2o("/root/reference/data/city10000.g2o")
else:
    from dpgo_tpu.utils.synthetic import make_measurements
    meas, _ = make_measurements(np.random.default_rng(0), n=100000, d=3,
                                num_lc=20000, rot_noise=0.01,
                                trans_noise=0.01)
edges = edge_set_from_measurements(meas, dtype=jnp.float64)
Xg = jnp.asarray(np.load("{xg_path}")["Xg"], jnp.float64)
c = certify.certify_solution(Xg, edges)
print(f"centralized f64: lambda_min={{c.lambda_min:.4e}} "
      f"sigma={{c.sigma:.3e}} stat={{c.stationarity_gap:.3e}} "
      f"certified={{c.certified}}")
"""
    out = subprocess.run([sys.executable, "-c", code], capture_output=True,
                         text=True, timeout=3600)
    log(out.stdout.strip() or out.stderr[-500:])


def run_cert(name, meas, A, r, rounds, num_probe=4, power_iters=50,
             sub_iters=100, validate=None):
    import jax
    import jax.numpy as jnp
    from dpgo_tpu.config import AgentParams, SolverParams
    from dpgo_tpu.models import rbcd
    from dpgo_tpu.parallel import certify as dcert
    from dpgo_tpu.parallel.sharded import make_mesh
    from dpgo_tpu.utils.partition import partition_contiguous

    params = AgentParams(d=meas.d, r=r, num_robots=A, rel_change_tol=0.0,
                         solver=SolverParams(grad_norm_tol=1e-9,
                                             max_inner_iters=10))
    part = partition_contiguous(meas, A)
    graph, meta = rbcd.build_graph(part, r, jnp.float32)
    X0 = rbcd.centralized_chordal_init(part, meta, graph, jnp.float32)
    state = rbcd.init_state(graph, meta, X0, params=params)
    t0 = time.perf_counter()
    state = rbcd.rbcd_steps(state, graph, rounds, meta, params)
    _ = np.asarray(state.X)
    log(f"[{name}] solve: {rounds} rounds in {time.perf_counter()-t0:.1f}s")

    mesh = make_mesh(1)
    # Compile outside the clock (bench convention).
    cert = dcert.certify_sharded(state.X, graph, mesh=mesh, eta=1e-4,
                                 num_probe=num_probe,
                                 power_iters=power_iters,
                                 sub_iters=sub_iters)
    t0 = time.perf_counter()
    cert = dcert.certify_sharded(state.X, graph, mesh=mesh, eta=1e-4,
                                 num_probe=num_probe,
                                 power_iters=power_iters,
                                 sub_iters=sub_iters)
    dt = time.perf_counter() - t0
    # Matvec count of the eigensolve: power shift (power_iters + 2) probes
    # of width 1, then sub_iters LOBPCG iterations, each applying S to the
    # [V R P] basis (3p columns) plus the Aop(V) residual (p), plus the
    # final Rayleigh-Ritz (p) and stationarity (r rows ride along).
    matvecs = (power_iters + 2) + sub_iters * (4 * num_probe) + num_probe + 1
    log(f"[{name}] certificate: lambda_min={cert.lambda_min:.4e} "
        f"sigma={cert.sigma:.3e} stat={cert.stationarity_gap:.3e} "
        f"certified={cert.certified} wall={dt:.2f}s "
        f"probes={num_probe} S-matvec-columns~{matvecs}")
    if validate is not None:
        Xg = rbcd.gather_to_global(state.X, graph,
                                   part.meas_global.num_poses)
        np.savez("/tmp/cert_xg.npz", Xg=np.asarray(Xg, np.float64))
        cpu_reference_cert("/tmp/cert_xg.npz", validate)
    return cert, dt


def city():
    from dpgo_tpu.utils.g2o import read_g2o
    meas = read_g2o("/root/reference/data/city10000.g2o")
    run_cert("city10000/32 r3", meas, 32, 3, 600, power_iters=200,
             sub_iters=300, validate="city")


def synth100k():
    from dpgo_tpu.utils.synthetic import make_measurements
    rng = np.random.default_rng(0)
    meas, _ = make_measurements(rng, n=100000, d=3, num_lc=20000,
                                rot_noise=0.01, trans_noise=0.01)
    run_cert("100k/64 r5", meas, 64, 5, 100, power_iters=100,
             sub_iters=200, validate="100k")


if __name__ == "__main__":
    which = sys.argv[1:] or ["city", "100k"]
    for w in which:
        {"city": city, "100k": synth100k}[w]()
