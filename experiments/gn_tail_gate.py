"""GN-tail vs BCD-floor A/B on the absolute-gradnorm gates (ROADMAP item
4 / ISSUE 9): does the preconditioned Gauss-Newton-CG centralized tail
(`models.refine.gn_tail`) break the block-coordinate stall that floors
ais2klinik (TPU arm gn 1.16) and the noisy-100k certification probe?

Protocol, per dataset arm:

1. Solve with the standard RBCD pipeline until the gradient-norm
   trajectory stalls (``refine.stall_handoff`` on the eval history, or
   the round cap) — the handoff iterate is the SHARED starting point.
2. Arm "bcd+": the same budget of additional plain BCD rounds (the
   block-coordinate floor the tail claims to break).
3. Arm "gn_tail": ``refine.gn_tail`` from the handoff iterate.

Reports centralized f64 gradient norms (the ``run_rbcd`` gate quantity)
at handoff and after each arm, and writes one JSON table
(``gn_tail_gate_results.json``) for BASELINE.md.

Usage:
  python experiments/gn_tail_gate.py [--rounds N] [--extra N]
      [--datasets ais2klinik,noisy2k,...]

Dataset arms (g2o files resolve under /root/reference/data when
present; synthetic arms build deterministically):
  ais2klinik  — the SE(2) absolute-gate dataset (skipped if the file is
                absent on this machine)
  noisy2k/noisy10k/noisy100k — the noisy synthetic certification probe
                at increasing scale (noise 0.1, 20% loop closures)
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

import jax
import numpy as np

jax.config.update("jax_enable_x64", True)

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

DATA_DIR = "/root/reference/data"


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def build_meas(name: str):
    from dpgo_tpu.utils.synthetic import make_measurements

    if name.startswith("noisy"):
        n = int(name[5:].replace("k", "000"))
        meas, _ = make_measurements(np.random.default_rng(7), n=n, d=3,
                                    num_lc=n // 5, rot_noise=0.1,
                                    trans_noise=0.1)
        return meas
    path = os.path.join(DATA_DIR, f"{name}.g2o")
    if not os.path.exists(path):
        return None
    from dpgo_tpu.utils.g2o import read_g2o

    return read_g2o(path)


def run_arm(name: str, rounds: int, extra: int, robots: int, rank: int):
    import jax.numpy as jnp
    from dpgo_tpu.config import AgentParams
    from dpgo_tpu.models import rbcd, refine
    from dpgo_tpu.types import edge_set_from_measurements

    meas = build_meas(name)
    if meas is None:
        log(f"[{name}] dataset file absent on this machine — skipped")
        return {"skipped": "dataset absent"}
    r = min(rank, 5) if meas.d == 3 else 3
    params = AgentParams(d=meas.d, r=r, num_robots=robots,
                         rel_change_tol=0.0)
    prob = rbcd.prepare_problem(meas, robots, params=params,
                                dtype=jnp.float64)
    edges_g = edge_set_from_measurements(prob.part.meas_global,
                                         dtype=jnp.float64)

    # Stage 1: BCD to the stall handoff.
    t0 = time.perf_counter()
    res = rbcd.dispatch_prepared(prob, max_iters=rounds, eval_every=5,
                                 grad_norm_tol=1e-12, verdict_every=20)
    handoff_rounds = res.iterations
    for k in range(8, len(res.grad_norm_history) + 1):
        if refine.stall_handoff(res.grad_norm_history[:k], window=8):
            handoff_rounds = 5 * k
            break
    gn_handoff = res.grad_norm_history[-1]
    Xg = np.asarray(rbcd.gather_to_global(jnp.asarray(res.X), prob.graph,
                                          prob.n_total), np.float64)
    t_bcd = time.perf_counter() - t0
    log(f"[{name}] handoff after {res.iterations} rounds "
        f"(stall at ~{handoff_rounds}): gn {gn_handoff:.4g} "
        f"({t_bcd:.1f}s)")

    # Arm A: more of the same BCD (the block floor).
    st = rbcd.init_state(prob.graph, prob.meta, jnp.asarray(res.X),
                         params=params)
    t0 = time.perf_counter()
    res_b = rbcd.dispatch_prepared(prob, max_iters=extra, eval_every=extra,
                                   grad_norm_tol=1e-12, state=st,
                                   verdict_every=extra)
    gn_bcd = res_b.grad_norm_history[-1]
    t_arm_a = time.perf_counter() - t0
    log(f"[{name}] bcd+{extra}: gn {gn_bcd:.4g} ({t_arm_a:.1f}s)")

    # Arm B: the GN-CG tail from the same handoff iterate.
    t0 = time.perf_counter()
    tail = refine.gn_tail(Xg, edges_g,
                          refine.GNTailConfig(max_outer=20,
                                              grad_norm_tol=0.1),
                          log=log)
    t_tail = time.perf_counter() - t0
    log(f"[{name}] gn_tail: gn {tail.grad_norm_history[-1]:.4g} "
        f"({tail.outer_iterations} outer / {tail.cg_iterations} CG, "
        f"{t_tail:.1f}s) terminated_by={tail.terminated_by}")
    return {
        "poses": int(meas.num_poses), "d": int(meas.d), "rank": r,
        "handoff_rounds": int(res.iterations),
        "gn_handoff": float(gn_handoff),
        "gn_bcd_extra": float(gn_bcd), "bcd_extra_rounds": int(extra),
        "bcd_extra_seconds": round(t_arm_a, 2),
        "gn_tail": float(tail.grad_norm_history[-1]),
        "gn_tail_outer": tail.outer_iterations,
        "gn_tail_cg": tail.cg_iterations,
        "gn_tail_seconds": round(t_tail, 2),
        "gn_tail_terminated_by": tail.terminated_by,
        "below_gate": bool(tail.grad_norm_history[-1] < 0.1),
    }


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--rounds", type=int, default=400,
                    help="BCD round cap before handoff")
    ap.add_argument("--extra", type=int, default=200,
                    help="extra BCD rounds for the floor arm")
    ap.add_argument("--robots", type=int, default=8)
    ap.add_argument("--rank", type=int, default=5)
    ap.add_argument("--datasets", default="ais2klinik,noisy2k")
    args = ap.parse_args()

    out = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "gn_tail_gate_results.json")
    results = {}
    if os.path.exists(out):  # merge: per-dataset arms accumulate
        with open(out) as f:
            results = json.load(f)
    for name in args.datasets.split(","):
        name = name.strip()
        if not name:
            continue
        results[name] = run_arm(name, args.rounds, args.extra,
                                args.robots, args.rank)
    print(json.dumps(results, indent=1))
    with open(out, "w") as f:
        json.dump(results, f, indent=1)


if __name__ == "__main__":
    main()
