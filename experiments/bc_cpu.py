"""Run bench_convergence's main on the f64 CPU backend (honest A/B arm —
same accelerated pipeline as the TPU run)."""
import sys
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)
sys.path.insert(0, "/root/repo")
import bench_convergence
bench_convergence.main()
