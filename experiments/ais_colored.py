import os, sys, time
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)
import jax.numpy as jnp
import numpy as np
from dpgo_tpu.config import AgentParams, Schedule, SolverParams
from dpgo_tpu.models import rbcd
from dpgo_tpu.ops import quadratic
from dpgo_tpu.types import edge_set_from_measurements
from dpgo_tpu.utils.g2o import read_g2o
from dpgo_tpu.utils.partition import partition_contiguous

meas = read_g2o("/root/reference/data/ais2klinik.g2o")
A = 32
part = partition_contiguous(meas, A)
edges_g = edge_set_from_measurements(part.meas_global, dtype=jnp.float64)
n = meas.num_poses
for sched in (Schedule.JACOBI, Schedule.COLORED):
    params = AgentParams(d=2, r=3, num_robots=A, schedule=sched,
                         rel_change_tol=0.0,
                         solver=SolverParams(grad_norm_tol=1e-12,
                                             max_inner_iters=10))
    graph, meta = rbcd.build_graph(part, 3, jnp.float64)
    X0 = rbcd.centralized_chordal_init(part, meta, graph, jnp.float64)
    state = rbcd.init_state(graph, meta, X0, params=params)
    costs = []
    t0 = time.time()
    rounds = 40 if sched == Schedule.JACOBI else 40 * meta.num_colors
    for it in range(rounds):
        state = rbcd.rbcd_step(state, graph, meta, params)
        if (it + 1) % (1 if sched == Schedule.JACOBI else meta.num_colors) == 0:
            f = float(quadratic.cost(
                rbcd.gather_to_global(state.X, graph, n), edges_g))
            costs.append(f)
    inc = sum(1 for a, b in zip(costs, costs[1:]) if b > a + 1e-9)
    print(f"{sched.value}: C={meta.num_colors} rounds={rounds} "
          f"f0={costs[0]:.0f} f_end={costs[-1]:.0f} increases={inc} "
          f"({time.time()-t0:.0f}s)  first5={[round(c) for c in costs[:5]]}",
          flush=True)
