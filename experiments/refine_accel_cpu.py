"""Plain vs accelerated refine cycles on sphere2500 (CPU; gap history)."""
import os, sys, time
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
import numpy as np
from dpgo_tpu.config import AgentParams, SolverParams
from dpgo_tpu.models import rbcd, refine
from dpgo_tpu.types import edge_set_from_measurements
from dpgo_tpu.utils.g2o import read_g2o
from dpgo_tpu.utils.partition import partition_contiguous

F_OPT = 843.5029071
meas = read_g2o("/root/reference/data/sphere2500.g2o")
params = AgentParams(d=3, r=5, num_robots=8, rel_change_tol=0.0,
                     solver=SolverParams(grad_norm_tol=1e-9,
                                         max_inner_iters=10))
part = partition_contiguous(meas, 8)
graph, meta = rbcd.build_graph(part, 5, jnp.float32)
X0 = rbcd.centralized_chordal_init(part, meta, graph, jnp.float32)
state = rbcd.init_state(graph, meta, X0, params=params)
t0 = time.time()
state = rbcd.rbcd_steps(state, graph, 150, meta, params)
edges_g = edge_set_from_measurements(part.meas_global, dtype=jnp.float32)
Xg = np.asarray(rbcd.gather_to_global(state.X, graph, meas.num_poses),
                np.float64)
print(f"descended 150 f32 rounds in {time.time()-t0:.1f}s; start gap "
      f"{refine.global_cost(refine._np_project_manifold(Xg, 3), edges_g)/F_OPT-1:.2e}",
      flush=True)
for accel in (False, True):
    t0 = time.time()
    X64, gap, cycles, hist = refine.solve_refine(
        Xg, graph, meta, params, edges_g, F_OPT, rel_gap=1e-6,
        rounds_per_cycle=50, max_cycles=8, accel=accel)
    print(f"accel={accel}: cycles={cycles} gap={gap:.2e} "
          f"hist={['%.1e' % h for h, _s in hist]} ({time.time()-t0:.1f}s)",
          flush=True)

for rpc in (100, 200, 300):
    t0 = time.time()
    X64, gap, cycles, hist = refine.solve_refine(
        Xg, graph, meta, params, edges_g, F_OPT, rel_gap=1e-6,
        rounds_per_cycle=rpc, max_cycles=6, accel=True)
    print(f"accel rpc={rpc}: cycles={cycles} gap={gap:.2e} "
          f"hist={['%.1e' % h for h, _s in hist]} ({time.time()-t0:.1f}s)",
          flush=True)
