"""Instrument the certified-gap refine phase: where does the ~0.3 s go?

Phases per cycle: verify (host f64 project + cost), recenter host build,
device transfers, fused refine rounds dispatch+readback, final verify.
"""
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

DATASET = "/root/reference/data/sphere2500.g2o"


def main():
    import jax
    import jax.numpy as jnp
    from dpgo_tpu.config import AgentParams, SolverParams
    from dpgo_tpu.models import rbcd, refine
    from dpgo_tpu.utils.g2o import read_g2o
    from dpgo_tpu.utils.partition import partition_contiguous

    dev = jax.devices()[0]
    print(f"device: {dev.platform} ({dev.device_kind})", file=sys.stderr)
    dtype = jnp.float32

    meas = read_g2o(DATASET)
    params = AgentParams(
        d=3, r=5, num_robots=8, rel_change_tol=0.0,
        acceleration=True, restart_interval=100,
        solver=SolverParams(grad_norm_tol=1e-9, max_inner_iters=10))
    part = partition_contiguous(meas, 8)
    graph, meta = rbcd.build_graph(part, 5, dtype)
    X0 = rbcd.centralized_chordal_init(part, meta, graph, dtype)
    state0 = rbcd.init_state(graph, meta, X0, params=params)
    # Host-f64 oracle edges, same as the tuned pipeline.
    edges_g = refine.host_edges_f64(part.meas_global)
    n_total = part.meas_global.num_poses

    # descend 125 rounds to the handoff (warm compile first)
    state = rbcd.rbcd_steps(state0, graph, 1, meta, params)
    state = rbcd.rbcd_steps(state, graph, 124, meta, params)
    Xg64_w = np.asarray(
        rbcd.gather_to_global(state.X, graph, n_total), np.float64)

    # warm-up: one full recenter + 2 fused rounds + readback
    ref_w = refine.recenter(Xg64_w, graph, meta, params, edges_g)
    _ = np.asarray(refine._refine_rounds_accel_jit(
        jnp.zeros(ref_w.consts.R.shape, jnp.float32),
        ref_w.consts, graph, meta, params, 2))

    # Timed, phase by phase (mirror solve_refine's single-cycle path)
    for trial in range(3):
        t = {}
        t0 = time.perf_counter()

        t1 = time.perf_counter()
        Xg64 = np.asarray(
            rbcd.gather_to_global(state.X, graph, n_total), np.float64)
        t["X_readback"] = time.perf_counter() - t1

        t1 = time.perf_counter()
        Xg64p = refine._np_project_manifold(Xg64, meta.d)
        t["verify_project"] = time.perf_counter() - t1

        t1 = time.perf_counter()
        f = refine.global_cost(Xg64p, edges_g)
        t["verify_cost"] = time.perf_counter() - t1

        t1 = time.perf_counter()
        ref = refine.recenter(Xg64p, graph, meta, params, edges_g,
                              pre_projected=True, f_ref=f)
        jax.block_until_ready(ref.consts.Rc)
        t["recenter_total"] = time.perf_counter() - t1

        t1 = time.perf_counter()
        D = refine._refine_rounds_accel_jit(
            jnp.zeros(ref.consts.R.shape, jnp.float32),
            ref.consts, graph, meta, params, 120)
        Dnp = np.asarray(D)
        t["rounds120_and_readback"] = time.perf_counter() - t1

        t1 = time.perf_counter()
        X64 = refine.global_x(ref, Dnp, graph)
        X64p = refine._np_project_manifold(X64, meta.d)
        f2 = refine.global_cost(X64p, edges_g)
        t["final_verify"] = time.perf_counter() - t1

        t["TOTAL"] = time.perf_counter() - t0
        print(json.dumps({k: round(v, 4) for k, v in t.items()}))

    # Sub-breakdown of recenter: host build vs device transfers
    for trial in range(2):
        t1 = time.perf_counter()
        ref = refine.recenter(Xg64_w, graph, meta, params, edges_g)
        host_done = time.perf_counter() - t1
        jax.block_until_ready(jax.tree.leaves(ref.consts))
        print(json.dumps({"recenter_host+enqueue": round(host_done, 4),
                          "recenter_blocked": round(
                              time.perf_counter() - t1, 4)}))


if __name__ == "__main__":
    main()
