"""Time-to-gate for BASELINE.json configs #1-#4 (VERDICT r3 item 2).

Runs each config to the reference driver's termination criterion —
centralized Riemannian gradient norm < 0.1
(``/root/reference/examples/MultiRobotExample.cpp:238``) — and records the
wall-clock to the gate on the TPU f32 arm and on this framework's own f64
CPU build (the reference's SuiteSparse/ROPTLIB dep is unavailable offline;
BASELINE.md).  Configs whose gradnorm plateaus above the gate (kitti_00's
near-chain graph) are run to a round cap on BOTH arms to show the plateau
is a property of block-coordinate descent on that graph, not of the arm.

Protocol: solve_rbcd with a per-config eval cadence (25 rounds on the
short configs; 300-500 on the long GNC runs, sized to the tunnel's
90 ms/readback — the evals are inside the clock: they are how the
driver decides to stop, exactly as the reference's centralized monitor
is), compile warmed by a short throwaway solve.  The CPU arm (a
subprocess — x64 cannot be enabled in the tunnel process; see bench.py)
keeps cadence <= 100: it pays no readback latency, and a coarse cadence
would only overshoot its gate crossings.

Usage: python experiments/time_to_gate.py [config_name ...]
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import numpy as np

sys.path.insert(0, "/root/repo")

DATA = "/root/reference/data"
GATE = 0.1

# name -> (file, agents, rank, schedule, robust, accel, eval_every,
#          tpu_cap, cpu_cap, hybrid).  Caps are asymmetric where the CPU
# arm's wall-clock at the same round count would run to hours: the CPU
# arm then records a BOUND (gradnorm still above gate after cpu_cap
# rounds / its wall) rather than a crossing.  ``hybrid`` enables the
# centralized A=1 continuation when the TPU arm plateaus above the gate.
CONFIGS = {
    # smallGrid: JACOBI + momentum diverges on this densely-coupled little
    # grid (gn 237 -> 2000 over 2000 rounds, both arms) — the classic
    # simultaneous-update instability; COLORED Gauss-Seidel + momentum is
    # stable, matching the reference's sequential greedy driver.
    "smallGrid": ("smallGrid3D.g2o", 5, 5, "colored", False, True, 25,
                  2000, 2000, True),
    "sphere2500": ("sphere2500.g2o", 8, 5, "jacobi", False, True, 25,
                   2000, 2000, True),
    # kitti_00: near-chain graph, BCD plateaus at gn ~27 from 648 on BOTH
    # arms (6000 rounds) — the gate is unreachable for block-coordinate
    # descent here regardless of arm; both rows document the bound.
    # Eval cadences on the long GNC runs are sized to the tunnel's 90 ms
    # readback: at cadence 100 the ais run paid ~600 evals = ~54 s of
    # pure round-trips out of 150 s; 300-500 trades <= one cadence of
    # overshoot (~1 s of rounds) for most of that.
    "kitti_00": ("kitti_00.g2o", 16, 3, "async", False, False, 300,
                 6000, 6000, True),
    "city10000_gnc": ("city10000.g2o", 32, 3, "jacobi", True, False, 300,
                      15000, 12000, True),
    # ais2klinik: hybrid excluded by measurement — A=1 rounds run at
    # ~2.8/s (15k poses, deep tCG) and 3000 of them moved gn only
    # 2.016 -> 2.004 for 1084 s; the gate row stands as a bound.
    "ais2klinik_gnc": ("ais2klinik.g2o", 32, 3, "colored", True, False, 500,
                       60000, 6000, False),
}


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def run_config(name: str):
    import jax
    import jax.numpy as jnp
    from dpgo_tpu.config import (AgentParams, RobustCostParams,
                                 RobustCostType, Schedule, SolverParams)
    from dpgo_tpu.models import rbcd
    from dpgo_tpu.utils.g2o import read_g2o

    fname, A, r, sched, robust, accel, ev, tpu_cap, cpu_cap, hybrid_ok = \
        CONFIGS[name]
    cpu = jax.devices()[0].platform == "cpu"
    dtype = jnp.float64 if cpu else jnp.float32
    cap = cpu_cap if cpu else tpu_cap
    if cpu:
        # The coarse cadences are sized to the tunnel's 90 ms readback,
        # which the CPU arm does not pay — and a 300-round cadence would
        # overshoot its gate crossing by up to ~26 s of f64 rounds.
        ev = min(ev, 100)
    meas = read_g2o(f"{DATA}/{fname}")
    params = AgentParams(
        d=meas.d, r=r, num_robots=A, schedule=Schedule(sched),
        robust=RobustCostParams(cost_type=RobustCostType.GNC_TLS)
        if robust else RobustCostParams(),
        rel_change_tol=0.0, acceleration=accel, restart_interval=100,
        # bf16x3 = f32-grade selection at fewer MXU passes (BASELINE.md
        # round-4 A/B); no effect on the f64 CPU arm (no kernel there).
        solver=SolverParams(pallas_sel_mode="bf16x3"),
    )

    # Warm-up: compile every program variant (init, segment flavors,
    # metrics) outside the clock — steady-state timing, bench.py
    # convention.  Must cross one eval boundary AND (accelerated) one
    # restart boundary: the restart-first segment variant compiles
    # separately, and a cold compile inside the clock once cost ~5 s of a
    # 7 s run.
    warm = 2 * ev if not accel else max(2 * ev, 100 + ev)
    _ = rbcd.solve_rbcd(meas, A, params, max_iters=warm, grad_norm_tol=0.0,
                        eval_every=ev, dtype=dtype)

    t0 = time.perf_counter()
    res = rbcd.solve_rbcd(meas, A, params, max_iters=cap, grad_norm_tol=GATE,
                          eval_every=ev, dtype=dtype)
    wall = time.perf_counter() - t0
    gn = float(res.grad_norm_history[-1])
    out = dict(config=name, arm="cpu_f64" if cpu else "tpu_f32",
               reached=bool(gn < GATE), gate=GATE, rounds=res.iterations,
               wall=round(wall, 2), final_gradnorm=gn,
               final_cost=float(res.cost_history[-1]),
               terminated_by=res.terminated_by)
    if not out["reached"] and not cpu and hybrid_ok \
            and os.environ.get("GATE_HYBRID", "1") == "1":
        hyb = centralized_continuation(meas, res, A, r, dtype, ev)
        if hyb is not None:
            hyb["wall"] = round(wall + hyb.pop("cont_wall"), 2)
            out["hybrid"] = hyb
    return out


def centralized_continuation(meas, res, A, r, dtype, ev):
    """Drive the gate on a BCD-plateaued graph with the centralized (A=1)
    engine: the per-measurement GNC weights from the distributed solve are
    frozen into the edges (the gate metric is the weighted centralized
    gradnorm either way), one block holds every pose, and deep-tCG RTR
    rounds crush the gradient modes block-coordinate descent cannot —
    the gate analog of bench_convergence.py's certified-gap fallback.
    """
    import dataclasses

    import jax
    import jax.numpy as jnp
    from dpgo_tpu.config import AgentParams, SolverParams
    from dpgo_tpu.models import rbcd
    from dpgo_tpu.ops import manifold, quadratic
    from dpgo_tpu.types import edge_set_from_measurements
    from dpgo_tpu.utils.partition import partition_contiguous

    # Freeze the distributed solve's final weights into the measurements.
    meas_w = meas
    if res.weights is not None:
        meas_w = dataclasses.replace(
            meas, weight=np.asarray(res.weights, np.float64))
    from dpgo_tpu.utils.partition import gather_poses_to_global

    Xg = jnp.asarray(gather_poses_to_global(res.X,
                                            partition_contiguous(meas, A)))

    part1 = partition_contiguous(meas_w, 1)
    graph1, meta1 = rbcd.build_graph(part1, r, dtype)
    params1 = AgentParams(
        d=meas.d, r=r, num_robots=1, rel_change_tol=0.0,
        solver=SolverParams(grad_norm_tol=1e-9, max_inner_iters=100,
                            pallas_sel_mode="bf16x3"))
    edges_g = edge_set_from_measurements(meas_w, dtype=dtype)

    @jax.jit
    def central_gn(Xa):
        Xg1 = rbcd.gather_to_global(Xa, graph1, meas.num_poses)
        g = manifold.rgrad(Xg1, quadratic.egrad(Xg1, edges_g))
        return manifold.norm(g)

    Xa = rbcd.scatter_to_agents(Xg, graph1)
    state = rbcd.init_state(graph1, meta1, Xa, params=params1)
    # A=1 deep-tCG rounds are expensive (a few per second on large
    # graphs), so the distributed run's eval cadence would overshoot the
    # gate by tens of seconds here — check at most every 100 rounds,
    # where <= 10 readbacks total are negligible.
    ev1 = min(ev, 100)
    # Warm-up compile outside the clock (steady-state convention).
    _ = float(central_gn(rbcd.rbcd_steps(state, graph1, 1, meta1,
                                         params1).X))
    t0 = time.perf_counter()
    rounds = 0
    gn = float("inf")
    while rounds < 3000:
        state = rbcd.rbcd_steps(state, graph1, ev1, meta1, params1)
        rounds += ev1
        gn = float(central_gn(state.X))
        if gn < GATE:
            break
    cont_wall = time.perf_counter() - t0
    log(f"    [hybrid] centralized continuation: gn {gn:.3f} after "
        f"{rounds} A=1 rounds / {cont_wall:.1f}s")
    return dict(reached=bool(gn < GATE), cont_rounds=rounds,
                final_gradnorm=gn, cont_wall=cont_wall)


def main():
    names = [a for a in sys.argv[1:] if not a.startswith("-")] \
        or list(CONFIGS)
    if os.environ.get("GATE_MODE") == "cpu":
        import jax
        jax.config.update("jax_platforms", "cpu")
        jax.config.update("jax_enable_x64", True)
        print(json.dumps(run_config(names[0])))
        return

    rows = []
    for name in names:
        row = run_config(name)
        log(f"[{name}] tpu: reached={row['reached']} rounds={row['rounds']} "
            f"wall={row['wall']}s gn={row['final_gradnorm']:.3f}")
        rows.append(row)
        if os.environ.get("GATE_SKIP_CPU") == "1":
            continue
        out = subprocess.run(
            [sys.executable, os.path.abspath(__file__), name],
            env=dict(os.environ, GATE_MODE="cpu", PYTHONPATH="/root/repo"),
            capture_output=True, text=True, timeout=7200)
        if out.returncode != 0:
            log(f"[{name}] cpu arm FAILED:\n{out.stderr[-1500:]}")
            continue
        crow = json.loads(out.stdout.strip().splitlines()[-1])
        log(f"[{name}] cpu: reached={crow['reached']} rounds={crow['rounds']} "
            f"wall={crow['wall']}s gn={crow['final_gradnorm']:.3f}")
        rows.append(crow)

    print("\n| config | arm | reached gate (gn<0.1) | rounds | wall | "
          "final gradnorm | hybrid (A=1 continuation) |")
    print("|---|---|---|---|---|---|---|")
    for w in rows:
        h = w.get("hybrid")
        hs = (f"reached={h['reached']} gn {h['final_gradnorm']:.3f} "
              f"total {h['wall']}s" if h else "—")
        print(f"| {w['config']} | {w['arm']} | {w['reached']} | {w['rounds']} "
              f"| {w['wall']}s | {w['final_gradnorm']:.3f} | {hs} |")
    # Merge-by-key into the existing results file: partial reruns (config
    # subsets, GATE_SKIP_CPU=1) must update their rows without dropping
    # the rest of the aggregate.
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "time_to_gate_results.json")
    merged: dict[tuple, dict] = {}
    if os.path.exists(path):
        with open(path) as f:
            for old in json.load(f):
                merged[(old["config"], old["arm"])] = old
    for w in rows:
        merged[(w["config"], w["arm"])] = w
    order = {n: i for i, n in enumerate(CONFIGS)}
    out_rows = sorted(merged.values(),
                      key=lambda w: (order.get(w["config"], 99), w["arm"]))
    with open(path, "w") as f:
        json.dump(out_rows, f, indent=1)


if __name__ == "__main__":
    main()
