"""Time-to-gate for BASELINE.json configs #1-#4 (VERDICT r3 item 2).

Runs each config to the reference driver's termination criterion —
centralized Riemannian gradient norm < 0.1
(``/root/reference/examples/MultiRobotExample.cpp:238``) — and records the
wall-clock to the gate on the TPU f32 arm and on this framework's own f64
CPU build (the reference's SuiteSparse/ROPTLIB dep is unavailable offline;
BASELINE.md).  Configs whose gradnorm plateaus above the gate (kitti_00's
near-chain graph) are run to a round cap on BOTH arms to show the plateau
is a property of block-coordinate descent on that graph, not of the arm.

Protocol: solve_rbcd with eval cadence 25-100 rounds (the eval readbacks
are inside the clock — they are how the driver decides to stop, exactly
as the reference's centralized monitor is), compile warmed by a short
throwaway solve.  CPU arm runs in a subprocess (x64 cannot be enabled in
the tunnel process; see bench.py).

Usage: python experiments/time_to_gate.py [config_name ...]
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import numpy as np

sys.path.insert(0, "/root/repo")

DATA = "/root/reference/data"
GATE = 0.1

# name -> (file, agents, rank, schedule, robust, accel, eval_every,
#          tpu_cap, cpu_cap).  Caps are asymmetric where the CPU arm's
# wall-clock at the same round count would run to hours: the CPU arm then
# records a BOUND (gradnorm still above gate after cpu_cap rounds / its
# wall) rather than a crossing.
CONFIGS = {
    # smallGrid: JACOBI + momentum diverges on this densely-coupled little
    # grid (gn 237 -> 2000 over 2000 rounds, both arms) — the classic
    # simultaneous-update instability; COLORED Gauss-Seidel + momentum is
    # stable, matching the reference's sequential greedy driver.
    "smallGrid": ("smallGrid3D.g2o", 5, 5, "colored", False, True, 25,
                  2000, 2000),
    "sphere2500": ("sphere2500.g2o", 8, 5, "jacobi", False, True, 25,
                   2000, 2000),
    # kitti_00: near-chain graph, BCD plateaus at gn ~27 from 648 on BOTH
    # arms (6000 rounds) — the gate is unreachable for block-coordinate
    # descent here regardless of arm; both rows document the bound.
    "kitti_00": ("kitti_00.g2o", 16, 3, "async", False, False, 100,
                 6000, 6000),
    "city10000_gnc": ("city10000.g2o", 32, 3, "jacobi", True, False, 100,
                      15000, 12000),
    "ais2klinik_gnc": ("ais2klinik.g2o", 32, 3, "colored", True, False, 100,
                       60000, 6000),
}


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def run_config(name: str):
    import jax
    import jax.numpy as jnp
    from dpgo_tpu.config import (AgentParams, RobustCostParams,
                                 RobustCostType, Schedule)
    from dpgo_tpu.models import rbcd
    from dpgo_tpu.utils.g2o import read_g2o

    fname, A, r, sched, robust, accel, ev, tpu_cap, cpu_cap = CONFIGS[name]
    cpu = jax.devices()[0].platform == "cpu"
    dtype = jnp.float64 if cpu else jnp.float32
    cap = cpu_cap if cpu else tpu_cap
    meas = read_g2o(f"{DATA}/{fname}")
    params = AgentParams(
        d=meas.d, r=r, num_robots=A, schedule=Schedule(sched),
        robust=RobustCostParams(cost_type=RobustCostType.GNC_TLS)
        if robust else RobustCostParams(),
        rel_change_tol=0.0, acceleration=accel, restart_interval=100,
    )

    # Warm-up: compile every program variant (init, segment flavors,
    # metrics) outside the clock — steady-state timing, bench.py
    # convention.  Must cross one eval boundary AND (accelerated) one
    # restart boundary: the restart-first segment variant compiles
    # separately, and a cold compile inside the clock once cost ~5 s of a
    # 7 s run.
    warm = 2 * ev if not accel else max(2 * ev, 100 + ev)
    _ = rbcd.solve_rbcd(meas, A, params, max_iters=warm, grad_norm_tol=0.0,
                        eval_every=ev, dtype=dtype)

    t0 = time.perf_counter()
    res = rbcd.solve_rbcd(meas, A, params, max_iters=cap, grad_norm_tol=GATE,
                          eval_every=ev, dtype=dtype)
    wall = time.perf_counter() - t0
    gn = float(res.grad_norm_history[-1])
    return dict(config=name, arm="cpu_f64" if cpu else "tpu_f32",
                reached=bool(gn < GATE), gate=GATE, rounds=res.iterations,
                wall=round(wall, 2), final_gradnorm=gn,
                final_cost=float(res.cost_history[-1]),
                terminated_by=res.terminated_by)


def main():
    names = [a for a in sys.argv[1:] if not a.startswith("-")] \
        or list(CONFIGS)
    if os.environ.get("GATE_MODE") == "cpu":
        import jax
        jax.config.update("jax_platforms", "cpu")
        jax.config.update("jax_enable_x64", True)
        print(json.dumps(run_config(names[0])))
        return

    rows = []
    for name in names:
        row = run_config(name)
        log(f"[{name}] tpu: reached={row['reached']} rounds={row['rounds']} "
            f"wall={row['wall']}s gn={row['final_gradnorm']:.3f}")
        rows.append(row)
        out = subprocess.run(
            [sys.executable, os.path.abspath(__file__), name],
            env=dict(os.environ, GATE_MODE="cpu", PYTHONPATH="/root/repo"),
            capture_output=True, text=True, timeout=7200)
        if out.returncode != 0:
            log(f"[{name}] cpu arm FAILED:\n{out.stderr[-1500:]}")
            continue
        crow = json.loads(out.stdout.strip().splitlines()[-1])
        log(f"[{name}] cpu: reached={crow['reached']} rounds={crow['rounds']} "
            f"wall={crow['wall']}s gn={crow['final_gradnorm']:.3f}")
        rows.append(crow)

    print("\n| config | arm | reached gate (gn<0.1) | rounds | wall | "
          "final gradnorm |")
    print("|---|---|---|---|---|---|")
    for w in rows:
        print(f"| {w['config']} | {w['arm']} | {w['reached']} | {w['rounds']} "
              f"| {w['wall']}s | {w['final_gradnorm']:.3f} |")
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "time_to_gate_results.json")
    with open(path, "w") as f:
        json.dump(rows, f, indent=1)


if __name__ == "__main__":
    main()
