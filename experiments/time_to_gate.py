"""Time-to-gate for BASELINE.json configs #1-#4 (VERDICT r3 item 2).

Runs each config to the reference driver's termination criterion —
centralized Riemannian gradient norm < 0.1
(``/root/reference/examples/MultiRobotExample.cpp:238``) — and records the
wall-clock to the gate on the TPU f32 arm and on this framework's own f64
CPU build (the reference's SuiteSparse/ROPTLIB dep is unavailable offline;
BASELINE.md).  Configs whose gradnorm plateaus above the gate (kitti_00's
near-chain graph) are run to a round cap on BOTH arms to show the plateau
is a property of block-coordinate descent on that graph, not of the arm.

Protocol: solve_rbcd with a per-config eval cadence (25 rounds on the
short configs; 300-500 on the long GNC runs, sized to the tunnel's
90 ms/readback — the evals are inside the clock: they are how the
driver decides to stop, exactly as the reference's centralized monitor
is), compile warmed by a short throwaway solve.  The CPU arm (a
subprocess — x64 cannot be enabled in the tunnel process; see bench.py)
keeps cadence <= 100: it pays no readback latency, and a coarse cadence
would only overshoot its gate crossings.

Usage: python experiments/time_to_gate.py [config_name ...]
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import numpy as np

sys.path.insert(0, "/root/repo")

DATA = "/root/reference/data"
GATE = 0.1

# name -> (file, agents, rank, schedule, robust, accel, eval_every,
#          tpu_cap, cpu_cap, hybrid).  Caps are asymmetric where the CPU
# arm's wall-clock at the same round count would run to hours: the CPU
# arm then records a BOUND (gradnorm still above gate after cpu_cap
# rounds / its wall) rather than a crossing.  ``hybrid`` enables the
# centralized A=1 continuation when the TPU arm plateaus above the gate.
CONFIGS = {
    # smallGrid: JACOBI + momentum diverges on this densely-coupled little
    # grid (gn 237 -> 2000 over 2000 rounds, both arms) — the classic
    # simultaneous-update instability; COLORED Gauss-Seidel + momentum is
    # stable, matching the reference's sequential greedy driver.
    "smallGrid": ("smallGrid3D.g2o", 5, 5, "colored", False, True, 25,
                  2000, 2000, True),
    "sphere2500": ("sphere2500.g2o", 8, 5, "jacobi", False, True, 25,
                   2000, 2000, True),
    # kitti_00: near-chain graph, BCD plateaus at gn ~27 from 648 on BOTH
    # arms (6000 rounds) — the gate is unreachable for block-coordinate
    # descent here regardless of arm; both rows document the bound.
    # Eval cadences on the long GNC runs are sized to the tunnel's 90 ms
    # readback: at cadence 100 the ais run paid ~600 evals = ~54 s of
    # pure round-trips out of 150 s; 300-500 trades <= one cadence of
    # overshoot (~1 s of rounds) for most of that.
    "kitti_00": ("kitti_00.g2o", 16, 3, "async", False, False, 300,
                 6000, 6000, True),
    "city10000_gnc": ("city10000.g2o", 32, 3, "jacobi", True, False, 300,
                      15000, 12000, True),
    # ais2klinik: MATCHED caps on both arms (VERDICT r4 item 5a — the
    # round-4 60000/6000 asymmetry made the CPU "bound" an
    # extrapolation), with the continuation enabled: the round-4
    # exclusion note (A=1 at 2.8 rounds/s moving gn 2.016 -> 2.004 over
    # 1084 s) described the momentum-less inner=100 continuation; the
    # round-5 momentum + recentered-cycle continuation is the machinery
    # that closed kitti's row on both arms.
    "ais2klinik_gnc": ("ais2klinik.g2o", 32, 3, "colored", True, False, 500,
                       12000, 12000, True),
}


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def run_config(name: str):
    import jax
    import jax.numpy as jnp
    from dpgo_tpu.config import (AgentParams, RobustCostParams,
                                 RobustCostType, Schedule, SolverParams)
    from dpgo_tpu.models import rbcd
    from dpgo_tpu.utils.g2o import read_g2o

    fname, A, r, sched, robust, accel, ev, tpu_cap, cpu_cap, hybrid_ok = \
        CONFIGS[name]
    cpu = jax.devices()[0].platform == "cpu"
    dtype = jnp.float64 if cpu else jnp.float32
    cap = cpu_cap if cpu else tpu_cap
    if cpu:
        # The coarse cadences are sized to the tunnel's 90 ms readback,
        # which the CPU arm does not pay — and a 300-round cadence would
        # overshoot its gate crossing by up to ~26 s of f64 rounds.
        ev = min(ev, 100)
    meas = read_g2o(f"{DATA}/{fname}")
    params = AgentParams(
        d=meas.d, r=r, num_robots=A, schedule=Schedule(sched),
        robust=RobustCostParams(cost_type=RobustCostType.GNC_TLS)
        if robust else RobustCostParams(),
        rel_change_tol=0.0, acceleration=accel, restart_interval=100,
        # bf16x3 = f32-grade selection at fewer MXU passes (BASELINE.md
        # round-4 A/B); no effect on the f64 CPU arm (no kernel there).
        solver=SolverParams(pallas_sel_mode="bf16x3"),
    )

    # Warm-up: compile every program variant (init, segment flavors,
    # metrics) outside the clock — steady-state timing, bench.py
    # convention.  Must cross one eval boundary AND (accelerated) one
    # restart boundary: the restart-first segment variant compiles
    # separately, and a cold compile inside the clock once cost ~5 s of a
    # 7 s run.
    warm = 2 * ev if not accel else max(2 * ev, 100 + ev)
    _ = rbcd.solve_rbcd(meas, A, params, max_iters=warm, grad_norm_tol=0.0,
                        eval_every=ev, dtype=dtype)

    t0 = time.perf_counter()
    res = rbcd.solve_rbcd(meas, A, params, max_iters=cap, grad_norm_tol=GATE,
                          eval_every=ev, dtype=dtype)
    wall = time.perf_counter() - t0
    gn = float(res.grad_norm_history[-1])
    out = dict(config=name, arm="cpu_f64" if cpu else "tpu_f32",
               reached=bool(gn < GATE), gate=GATE, rounds=res.iterations,
               wall=round(wall, 2), final_gradnorm=gn,
               final_cost=float(res.cost_history[-1]),
               terminated_by=res.terminated_by)
    if not out["reached"] and hybrid_ok \
            and os.environ.get("GATE_HYBRID", "1") == "1":
        # Both arms run the SAME continuation protocol (VERDICT r4 item 5:
        # every "no" row needs same-protocol evidence on both arms).
        hyb = centralized_continuation(meas, res, A, r, dtype, ev)
        if hyb is not None:
            hyb["wall"] = round(wall + hyb.pop("cont_wall"), 2)
            out["hybrid"] = hyb
    return out


def centralized_continuation(meas, res, A, r, dtype, ev):
    """Drive the gate on a BCD-plateaued graph with the centralized (A=1)
    engine: the per-measurement GNC weights from the distributed solve are
    frozen into the edges (the gate metric is the weighted centralized
    gradnorm either way), one block holds every pose, and deep-tCG RTR
    rounds crush the gradient modes block-coordinate descent cannot —
    the gate analog of bench_convergence.py's certified-gap fallback.
    """
    import dataclasses

    import jax
    import jax.numpy as jnp
    from dpgo_tpu.config import AgentParams, SolverParams
    from dpgo_tpu.models import rbcd
    from dpgo_tpu.ops import manifold, quadratic
    from dpgo_tpu.types import edge_set_from_measurements
    from dpgo_tpu.utils.partition import partition_contiguous

    # Release the distributed phase's device buffers and compiled
    # executables first: on the 15k-pose ais graph the 32-agent programs
    # plus the A=1 continuation programs together exhaust the chip and
    # crash the TPU worker outright (reproduced round 5; isolated runs of
    # either phase are fine).  The recompile this forces is outside any
    # reported number's clock-critical path.
    import gc
    jax.clear_caches()
    gc.collect()

    # Freeze the distributed solve's final weights into the measurements.
    meas_w = meas
    if res.weights is not None:
        meas_w = dataclasses.replace(
            meas, weight=np.asarray(res.weights, np.float64))
    from dpgo_tpu.utils.partition import gather_poses_to_global

    Xg = jnp.asarray(gather_poses_to_global(res.X,
                                            partition_contiguous(meas, A)))

    # Near-centralized block count: A=1 is the true centralized engine;
    # when it does not fit (the single-block 15k-pose ais program also
    # reproducibly crashes the tunneled TPU worker), take the SMALLEST
    # block count whose per-block problem fits the refine VMEM kernel —
    # on TPU the kernel is ~15x faster per refine round than the XLA
    # fallback at these sizes (kitti A=1: 90 rounds/s kernel vs ais A=2:
    # 0.7 s/round XLA, measured round 5), and few big Gauss-Seidel
    # blocks keep near-centralized conditioning.
    from dpgo_tpu.config import Schedule
    from dpgo_tpu.models import refine as rmod
    on_cpu = jax.devices()[0].platform == "cpu"
    if on_cpu:
        A_cont = 1
        part1 = partition_contiguous(meas_w, A_cont)
        graph1, meta1 = rbcd.build_graph(part1, r, dtype)
    else:
        for A_cont in (1, 2, 3, 4, 6, 8, 12, 16):
            if A_cont == 1 and meas.num_poses > 8000:
                continue  # worker-crash regime, see above
            part1 = partition_contiguous(meas_w, A_cont)
            graph1, meta1 = rbcd.build_graph(part1, r, dtype)
            if graph1.eidx_i is not None \
                    and rbcd._pallas_vmem_ok(meta1, graph1) \
                    and rmod._refine_kernel_fits(graph1, meta1):
                break
        log(f"    [hybrid] continuation block count A={A_cont}")
    params1 = AgentParams(
        d=meas.d, r=r, num_robots=A_cont, rel_change_tol=0.0,
        schedule=Schedule("colored") if A_cont > 1 else Schedule("jacobi"),
        # (kept below: momentum + moderate tCG — see docstring)
        # Nesterov + moderate tCG, not plain deep-tCG rounds: the round-4
        # continuation (inner=100, no momentum) crawled — kitti floored
        # at gn 2.2 after 3000 rounds and ais moved 2.016 -> 2.004 in
        # 1084 s.  The refine-phase lesson (bench_convergence fallback,
        # BASELINE.md parking-garage) is that the momentum horizon, not
        # tCG depth, is the lever on condition-limited graphs.
        acceleration=True, restart_interval=100,
        solver=SolverParams(grad_norm_tol=1e-9, max_inner_iters=20,
                            pallas_sel_mode="bf16x3"))
    edges_g = edge_set_from_measurements(meas_w, dtype=dtype)

    @jax.jit
    def central_gn(Xa):
        Xg1 = rbcd.gather_to_global(Xa, graph1, meas.num_poses)
        g = manifold.rgrad(Xg1, quadratic.egrad(Xg1, edges_g))
        return manifold.norm(g)

    Xa = rbcd.scatter_to_agents(Xg, graph1)
    state = rbcd.init_state(graph1, meta1, Xa, params=params1)
    # A=1 deep-tCG rounds are expensive (a few per second on large
    # graphs), so the distributed run's eval cadence would overshoot the
    # gate by tens of seconds here — check at most every 100 rounds,
    # where <= 10 readbacks total are negligible.
    ev1 = min(ev, 100)
    # Warm-up compile outside the clock (steady-state convention); both
    # segment flavors (plain + restart-first) compile separately.
    _ = float(central_gn(rbcd.rbcd_segment(state, graph1, 1, meta1,
                                           params1,
                                           first_restart=False).X))
    _ = rbcd.rbcd_segment(state, graph1, 1, meta1, params1,
                          first_restart=True)
    t0 = time.perf_counter()
    rounds = 0
    gn = float("inf")
    gn_prev = float("inf")
    while rounds < 3000:
        # Momentum restart at each block boundary (ev1 == the restart
        # cadence): mirrors bench_convergence.advance()'s segmentation.
        state = rbcd.rbcd_segment(state, graph1, ev1, meta1, params1,
                                  first_restart=rounds > 0)
        rounds += ev1
        gn = float(central_gn(state.X))
        if gn < GATE:
            break
        if dtype == jnp.float32 and gn > 0.9 * gn_prev \
                and rounds >= 3 * ev1:
            # Contraction stalled (< 10% per block): on the f32 arm this
            # is the gradient-noise floor (kitti: plateaus at gn ~2.2
            # where the SAME continuation in f64 passes through to the
            # gate — measured round 5), so fall through to the
            # re-centered cycles below rather than burn the cap.
            break
        gn_prev = gn
    out = dict(reached=bool(gn < GATE), cont_rounds=rounds,
               final_gradnorm=gn)

    if gn >= GATE and dtype == jnp.float32:
        # Re-centered continuation: the f32 floor is eps*|G| gradient
        # noise; the recentered refine rounds (models.refine) hold the
        # large terms as f64-computed constants so the effective floor
        # drops by orders of magnitude — the gate analog of the
        # certified-gap pipeline's refine phase.  Gate checks run on the
        # HOST in f64 from the assembled iterate (one readback per
        # cycle, negligible at gate time scales).
        from dpgo_tpu.models import refine as rmod
        edges_np = rmod.host_edges_f64(meas_w)
        Xg64 = np.asarray(rbcd.gather_to_global(state.X, graph1,
                                                meas.num_poses),
                          np.float64)
        e64 = rmod.np_edges_batched(edges_np)
        d = meas.d

        def central_gn64(Xg64p):
            return rmod.central_gradnorm64(Xg64p, e64, meas.num_poses, d)

        chol = None
        cycles = 0
        # Long cycles: Nesterov's effective horizon is the cycle length
        # (momentum restarts at D=0 each recenter), and kitti's
        # near-chain conditioning needs hundreds of rounds of horizon —
        # 150-round cycles stalled at gn 0.44 where 400-round cycles
        # pass the gate (measured round 5).  Cycle-boundary safeguard
        # (solve_refine's): momentum over simultaneous block updates can
        # diverge on strongly-coupled graphs (ais went gn -> nan without
        # it) — revert to the best verified iterate and continue with
        # plain (un-accelerated) refine rounds.
        import jax.numpy as jnp2
        best = None
        # Staged operator ladder, one demotion per oscillation trip:
        # jacobi+momentum (fastest; diverges on strongly-coupled graphs)
        # -> colored sweeps+momentum (sequential stability WITH the
        # momentum horizon — the round-5 addition that moves ais where
        # plain colored crawled at ~0.3 gn/cycle) -> plain colored.
        modes = ["jacobi_accel", "colored_accel", "colored"]
        mode_i = 0
        for cycles in range(1, 31):
            if np.isfinite(Xg64).all():
                Xg64 = rmod._np_project_manifold(Xg64, d)
                gn = central_gn64(Xg64)
            else:
                gn = float("nan")
            log(f"      [recentered] cycle {cycles}: gn "
                f"{gn:.4f} (mode={modes[mode_i]})")
            if best is not None and not (gn < best[0] * 1.02):
                mode_i = min(mode_i + 1, len(modes) - 1)
                Xg64, gn = best[1], best[0]
                continue
            if best is None or gn < best[0]:
                best = (gn, Xg64)
            if gn < GATE:
                break
            ref = rmod.recenter(Xg64, graph1, meta1, params1, edges_np,
                                chol=chol, pre_projected=True)
            chol = ref.consts.chol
            D0 = jnp2.zeros(ref.consts.R.shape, jnp2.float32)
            mode = modes[mode_i]
            if mode == "jacobi_accel":
                D = rmod.refine_rounds_accel_chunked(
                    D0, ref.consts, graph1, meta1, params1, 400,
                    chunk=100)
            elif mode == "colored_accel":
                D = rmod.refine_rounds_accel_colored_chunked(
                    D0, ref.consts, graph1, meta1, params1, 400,
                    chunk=100)
            else:
                D = D0
                for _ in range(4):
                    D = rmod._refine_rounds_colored_jit(
                        D, ref.consts, graph1, meta1, params1, 100)
            Xg64 = rmod.global_x(ref, np.asarray(D), graph1)
        out.update(recentered_cycles=cycles, final_gradnorm=gn,
                   reached=bool(gn < GATE))

    cont_wall = time.perf_counter() - t0
    log(f"    [hybrid] centralized continuation: gn {gn:.3f} after "
        f"{out['cont_rounds']} A=1 rounds"
        + (f" + {out.get('recentered_cycles', 0)} recentered cycles"
           if out.get("recentered_cycles") else "")
        + f" / {cont_wall:.1f}s")
    out["cont_wall"] = cont_wall
    return out


def main():
    names = [a for a in sys.argv[1:] if not a.startswith("-")] \
        or list(CONFIGS)
    if os.environ.get("GATE_MODE") == "cpu":
        import jax
        jax.config.update("jax_platforms", "cpu")
        jax.config.update("jax_enable_x64", True)
        print(json.dumps(run_config(names[0])))
        return

    rows = []
    for name in names:
        row = run_config(name)
        log(f"[{name}] tpu: reached={row['reached']} rounds={row['rounds']} "
            f"wall={row['wall']}s gn={row['final_gradnorm']:.3f}")
        rows.append(row)
        if os.environ.get("GATE_SKIP_CPU") == "1":
            continue
        out = subprocess.run(
            [sys.executable, os.path.abspath(__file__), name],
            env=dict(os.environ, GATE_MODE="cpu", PYTHONPATH="/root/repo"),
            capture_output=True, text=True, timeout=7200)
        if out.returncode != 0:
            log(f"[{name}] cpu arm FAILED:\n{out.stderr[-1500:]}")
            continue
        crow = json.loads(out.stdout.strip().splitlines()[-1])
        log(f"[{name}] cpu: reached={crow['reached']} rounds={crow['rounds']} "
            f"wall={crow['wall']}s gn={crow['final_gradnorm']:.3f}")
        rows.append(crow)

    print("\n| config | arm | reached gate (gn<0.1) | rounds | wall | "
          "final gradnorm | hybrid (A=1 continuation) |")
    print("|---|---|---|---|---|---|---|")
    for w in rows:
        h = w.get("hybrid")
        hs = (f"reached={h['reached']} gn {h['final_gradnorm']:.3f} "
              f"total {h['wall']}s" if h else "—")
        print(f"| {w['config']} | {w['arm']} | {w['reached']} | {w['rounds']} "
              f"| {w['wall']}s | {w['final_gradnorm']:.3f} | {hs} |")
    # Merge-by-key into the existing results file: partial reruns (config
    # subsets, GATE_SKIP_CPU=1) must update their rows without dropping
    # the rest of the aggregate.
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "time_to_gate_results.json")
    merged: dict[tuple, dict] = {}
    if os.path.exists(path):
        with open(path) as f:
            for old in json.load(f):
                merged[(old["config"], old["arm"])] = old
    for w in rows:
        merged[(w["config"], w["arm"])] = w
    order = {n: i for i, n in enumerate(CONFIGS)}
    out_rows = sorted(merged.values(),
                      key=lambda w: (order.get(w["config"], 99), w["arm"]))
    with open(path, "w") as f:
        json.dump(out_rows, f, indent=1)


if __name__ == "__main__":
    main()
