"""Per-edge/per-pose VPU breakdown at the 100k/64 shape (VERDICT r4
item 6: "restructure the per-edge VPU math").

Round 4 established the kernel is VPU/loop-bound after the selection
split + paired tiles; round 5 relocated the bottleneck to MXU dot issue
and promoted packed selection + mode-gated wide tiles; round 6 DECIDED
the surviving gates (see VARIANTS below).  Each variant runs in a fresh
subprocess against the SAME problem:

* ``ns8``     — PALLAS_NS_SWEEPS=8: the retraction's Newton-Schulz polar
  runs 24 fixed sweeps (~1.9k [n]-wide FMAs, sized for near-singular
  M = X + eta); a trust-region step is never near-singular, so 8 sweeps
  reach f32-grade orthonormality (drift checked below).  Decision
  standing: default stays 24 (drift not worth ~5-7%).
* ``t128``/``t512`` — PALLAS_TILE (DPGO_AB-scoped): tile-width sweep
  around the promoted mode-gated T=256 default.
* ``inner2``  — max_inner_iters=2 (vs the production 10): NOT a
  candidate (changes semantics) — isolates per-tCG-iteration cost.

Parity: every variant reports the f64 global cost after 60 rounds; a
variant is acceptable only within 1e-5 relative of the baseline arm.

Usage: python experiments/kernel_breakdown.py [rounds]
       (worker: KB_MODE=worker KB_VARIANT=... internal)
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import numpy as np

sys.path.insert(0, "/root/repo")

# Round-6 A/B DECISIONS (recorded in BASELINE.md):
#   * PALLAS_SEL_PACKED — promoted: packed selection is unconditional in
#     ops.pallas_tcg; the unpacked code path and its gate are DELETED
#     (winner at every measured shape: bf16x3 100k/64 36.7 -> 57.6).
#   * PALLAS_UNROLL_TILES — deleted: measured dead end (Mosaic keeps all
#     unrolled tiles' one-hot transients live; scoped VMEM 16.55M > 16M
#     at T=128 bf16x3, 36.1M with t256+f32).
#   * PALLAS_NS_SWEEPS — kept (the one remaining live gate): default
#     stays 24 sweeps; ns8's ~5-7% is not worth 7e-4..2.6e-3 drift.
#   * PALLAS_TILE — kept (DPGO_AB-scoped): T=512 read within hour noise
#     of the promoted T=256 default, which keeps 2x the VMEM headroom.
#
# "base" = the production defaults; the remaining variants measure the
# two surviving knobs.
VARIANTS = {
    "base": {},
    "t128": {"PALLAS_TILE": "128"},
    "t512": {"PALLAS_TILE": "512"},
    "ns8": {"PALLAS_NS_SWEEPS": "8"},
}


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def worker():
    import jax
    import jax.numpy as jnp
    from dpgo_tpu.config import AgentParams, SolverParams
    from dpgo_tpu.models import rbcd, refine
    from dpgo_tpu.utils.partition import partition_contiguous
    from dpgo_tpu.utils.synthetic import make_measurements

    rounds = int(os.environ.get("KB_ROUNDS", "60"))
    inner = int(os.environ.get("KB_INNER", "10"))
    sel = os.environ.get("KB_SEL", "f32")
    rng = np.random.default_rng(0)
    meas, _ = make_measurements(rng, n=100000, d=3, num_lc=20000,
                                rot_noise=0.01, trans_noise=0.01)
    A, r = 64, 3
    params = AgentParams(d=3, r=r, num_robots=A,
                         solver=SolverParams(pallas_sel_mode=sel,
                                             max_inner_iters=inner))
    part = partition_contiguous(meas, A)
    graph, meta = rbcd.build_graph(part, r, jnp.float32, sel_mode=sel)
    X0 = rbcd.centralized_chordal_init(part, meta, graph, jnp.float32)
    state = rbcd.init_state(graph, meta, X0, params=params)
    form = rbcd._formulation(meta, params, graph)
    assert form == "pallas", form
    steps = lambda s, k: rbcd.rbcd_steps(s, graph, k, meta, params)
    # Timing convention (bench.py / selmode_100k): end with a REAL
    # readback — the tunneled TPU's block_until_ready returns early.
    t0 = time.perf_counter()
    st = steps(state, 1)
    _ = np.asarray(st.X)
    compile_s = time.perf_counter() - t0
    _ = np.asarray(steps(st, min(20, rounds)).X)
    rates = []
    for _ in range(3):
        t0 = time.perf_counter()
        out = steps(state, rounds)
        _ = np.asarray(out.X)
        rates.append(rounds / (time.perf_counter() - t0))
    # Parity: f64 cost of the 60-round iterate on the global edge set.
    st60 = steps(state, 60)
    Xg = np.asarray(rbcd.gather_to_global(st60.X, graph,
                                          part.meas_global.num_poses),
                    np.float64)
    from dpgo_tpu.types import edge_set_from_measurements
    edges = edge_set_from_measurements(part.meas_global, dtype=jnp.float64)
    f60 = float(refine.global_cost(Xg, edges))
    print(json.dumps(dict(rounds_per_s=round(float(np.median(rates)), 2),
                          rates=[round(x, 2) for x in rates],
                          compile_s=round(compile_s, 1), f60=f60)))


def main():
    if os.environ.get("KB_MODE") == "worker":
        worker()
        return
    rounds = sys.argv[1] if len(sys.argv) > 1 else "60"
    results = {}
    for sel in ("f32", "bf16x3"):
        for name, env in VARIANTS.items():
            # PYTHONPATH must APPEND: /root/.axon_site hosts the
            # axon-tunnel sitecustomize (see verify SKILL.md).
            # DPGO_AB=1 opts into the A/B env gates (PALLAS_TILE et al.
            # are ignored in production shells without it).
            e = dict(os.environ, KB_MODE="worker", KB_ROUNDS=rounds,
                     KB_SEL=sel, DPGO_AB="1",
                     PYTHONPATH="/root/.axon_site:/root/repo", **env)
            t0 = time.perf_counter()
            out = subprocess.run([sys.executable, os.path.abspath(__file__)],
                                 env=e, capture_output=True, text=True,
                                 timeout=1800)
            if out.returncode != 0:
                log(f"[{sel}/{name}] FAILED:\n{out.stderr[-800:]}")
                results[f"{sel}/{name}"] = dict(error=out.stderr[-200:])
                continue
            row = json.loads(out.stdout.strip().splitlines()[-1])
            base = results.get(f"{sel}/base")
            if base and "f60" in base:
                row["f60_rel_drift"] = abs(row["f60"] - base["f60"]) / base["f60"]
            results[f"{sel}/{name}"] = row
            log(f"[{sel}/{name}] {row['rounds_per_s']} rounds/s "
                f"(wall {time.perf_counter()-t0:.0f}s, "
                f"drift {row.get('f60_rel_drift', 0):.2e})")
    # Per-iteration isolation on the winning f32 variant.
    for inner in ("10", "2"):
        e = dict(os.environ, KB_MODE="worker", KB_ROUNDS=rounds, KB_SEL="f32",
                 KB_INNER=inner,
                 PYTHONPATH="/root/.axon_site:/root/repo")
        out = subprocess.run([sys.executable, os.path.abspath(__file__)],
                             env=e, capture_output=True, text=True,
                             timeout=1800)
        if out.returncode == 0:
            row = json.loads(out.stdout.strip().splitlines()[-1])
            results[f"f32/inner{inner}"] = row
            log(f"[f32/inner{inner}] {row['rounds_per_s']} rounds/s")
    print(json.dumps(results, indent=1))
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "kernel_breakdown_results.json")
    with open(path, "w") as f:
        json.dump(results, f, indent=1)


if __name__ == "__main__":
    main()
