"""Config #5 end-to-end: rank staircase r=3->7 + certification at 100k/64
on TPU (VERDICT r3 item 4 / BASELINE.json config #5).

The staircase is beyond-reference (certification is not implemented in the
reference code; BASELINE.md) — scoped from the T-RO paper: at each rank,
solve sharded RBCD over the agent mesh, run the distributed dual
certificate (block LOBPCG), and on failure lift along the negative
curvature direction (``parallel.certify.solve_staircase_sharded``).

The 100k synthetic stands in for the stripped g2o100k dataset
(``/root/reference/.MISSING_LARGE_BLOBS``) — same generator/seed as the
round-3 certification benchmark (``experiments/cert_scale.py``).

Usage: python experiments/staircase_100k.py [rounds_per_rank]
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, "/root/repo")


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def main():
    import jax
    from dpgo_tpu.parallel import certify as dcert
    from dpgo_tpu.utils.synthetic import make_measurements

    rounds = int(sys.argv[1]) if len(sys.argv) > 1 else 200
    log("generating 100k-pose synthetic (seed 0, as cert_scale.py) ...")
    rng = np.random.default_rng(0)
    meas, _ = make_measurements(rng, n=100000, d=3, num_lc=20000,
                                rot_noise=0.01, trans_noise=0.01)
    dev = jax.devices()[0]
    log(f"device: {dev.platform} ({dev.device_kind}); staircase r=3->7, "
        f"{rounds} rounds/rank, 64 agents")

    t0 = time.perf_counter()
    T, Xa, rank, cert, hist = dcert.solve_staircase_sharded(
        meas, 64, r_min=3, r_max=7, rounds_per_rank=rounds, verbose=True)
    total = time.perf_counter() - t0

    rows = [dict(rank=r, cost=f, lambda_min=lam, wall_s=w)
            for r, f, lam, w in hist]
    out = dict(metric="staircase_100k_64agents_r3to7",
               certified=bool(cert.certified), final_rank=rank,
               total_s=round(total, 1), per_rank=rows)
    log(f"final rank {rank}, certified={cert.certified}, "
        f"total {total:.1f}s")
    print(json.dumps(out))
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "staircase_100k_results.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=1)


if __name__ == "__main__":
    main()
