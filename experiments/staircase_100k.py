"""Config #5 end-to-end: rank staircase r=3->7 + certification at 100k/64
on TPU (VERDICT r3 item 4 / BASELINE.json config #5).

The staircase is beyond-reference (certification is not implemented in the
reference code; BASELINE.md) — scoped from the T-RO paper: at each rank,
solve sharded RBCD over the agent mesh, run the distributed dual
certificate (block LOBPCG), and on failure lift along the negative
curvature direction (``parallel.certify.solve_staircase_sharded``).

The 100k synthetic stands in for the stripped g2o100k dataset
(``/root/reference/.MISSING_LARGE_BLOBS``) — same generator/seed as the
round-3 certification benchmark (``experiments/cert_scale.py``).

Usage: python experiments/staircase_100k.py [rounds_per_rank]
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, "/root/repo")


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def main():
    import jax
    from dpgo_tpu.parallel import certify as dcert
    from dpgo_tpu.utils.synthetic import make_measurements

    # --noise X: the high-noise probe (round-4 table ran 0.3 — the row
    # whose "-2.45 certified" the round-5 weight-scale tolerance + f64
    # verification must re-decide; VERDICT r4 item 3).
    noise = 0.01
    argv = sys.argv[1:]
    if "--noise" in argv:
        i = argv.index("--noise")
        noise = float(argv[i + 1])
        argv = argv[:i] + argv[i + 2:]   # drop the flag AND its value
    rounds = int(argv[0]) if argv else 600
    log(f"generating 100k-pose synthetic (seed 0, noise {noise}) ...")
    rng = np.random.default_rng(0)
    meas, _ = make_measurements(rng, n=100000, d=3, num_lc=20000,
                                rot_noise=noise, trans_noise=noise)
    dev = jax.devices()[0]
    log(f"device: {dev.platform} ({dev.device_kind}); staircase r=3->7, "
        f"{rounds} rounds/rank, 64 agents")

    t0 = time.perf_counter()
    # r_max 4 (was 7 round-4): under the honest certificate a refusal
    # driven by stationarity (not curvature) repeats identically at
    # every higher rank — climbing cannot fix a gradient floor, so two
    # levels suffice to characterize the probe.
    T, Xa, rank, cert, hist = dcert.solve_staircase_sharded(
        meas, 64, r_min=3, r_max=4, rounds_per_rank=rounds, accel=True,
        verbose=True)
    total = time.perf_counter() - t0

    rows = [dict(rank=r, cost=f, lambda_min=lam, wall_s=w)
            for r, f, lam, w in hist]
    out = dict(metric="staircase_100k_64agents_r3to7", noise=noise,
               certified=bool(cert.certified), final_rank=rank,
               lambda_min=cert.lambda_min, tol=cert.tol,
               decidable=cert.decidable, lambda_min_f64=cert.lambda_min_f64,
               total_s=round(total, 1), per_rank=rows)
    log(f"final rank {rank}, certified={cert.certified}, "
        f"total {total:.1f}s")
    print(json.dumps(out))
    suffix = "" if noise == 0.01 else f"_noise{noise}"
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        f"staircase_100k{suffix}_results.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=1)


if __name__ == "__main__":
    main()
