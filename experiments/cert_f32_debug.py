"""Reproduce + fix the f32 distributed-certificate failure at scale, on
the CPU mesh (fast iteration, no TPU)."""
import os, sys
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
os.environ["JAX_PLATFORMS"] = "cpu"
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)  # f64 AVAILABLE; graph in f32
import jax.numpy as jnp
import numpy as np
from dpgo_tpu.config import AgentParams, SolverParams
from dpgo_tpu.models import certify, rbcd
from dpgo_tpu.parallel import certify as dcert
from dpgo_tpu.parallel.sharded import make_mesh
from dpgo_tpu.types import edge_set_from_measurements
from dpgo_tpu.utils.partition import partition_contiguous
from dpgo_tpu.utils.synthetic import make_measurements

rng = np.random.default_rng(0)
# noise 0.01 -> kappa ~ 1e4 like the 100k synthetic; 20k poses, 16 agents
meas, _ = make_measurements(rng, n=50000, d=3, num_lc=10000,
                            rot_noise=0.003, trans_noise=0.003)
part = partition_contiguous(meas, 32)
params = AgentParams(d=3, r=5, num_robots=32, rel_change_tol=0.0,
                     solver=SolverParams(grad_norm_tol=1e-9,
                                         max_inner_iters=10))
graph32, meta = rbcd.build_graph(part, 5, jnp.float32)
X0 = rbcd.centralized_chordal_init(part, meta, graph32, jnp.float32)
state = rbcd.init_state(graph32, meta, X0, params=params)
state = rbcd.rbcd_steps(state, graph32, 100, meta, params)
X32 = state.X

# f64 truth (centralized)
edges64 = edge_set_from_measurements(part.meas_global, dtype=jnp.float64)
Xg = rbcd.gather_to_global(jnp.asarray(X32, jnp.float64), graph32,
                           meas.num_poses)
c = certify.certify_solution(Xg, edges64)
print(f"centralized f64: lam={c.lambda_min:.4e} sigma={c.sigma:.3e} "
      f"stat={c.stationarity_gap:.3e}", flush=True)

cd = dcert.certify_sharded(X32, graph32, mesh=make_mesh(8), eta=1e-4,
                           power_iters=100, sub_iters=200)
print(f"distributed f32: lam={cd.lambda_min:.4e} sigma={cd.sigma:.3e} "
      f"stat={cd.stationarity_gap:.3e}", flush=True)
