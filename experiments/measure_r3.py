"""Round-3 perf calibration: measure today's tunnel throughput on every
benchmark config plus an ablation breakdown of one fused sphere2500 round.

Usage: python experiments/measure_r3.py [sphere kitti city 100k ablate] ...
(one process — the tunneled TPU has a single grant).
"""

from __future__ import annotations

import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

DATA = "/root/reference/data"


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def build(meas, A, r, dtype, schedule=None, bf16=False):
    import jax.numpy as jnp
    from dpgo_tpu.config import AgentParams, Schedule, SolverParams
    from dpgo_tpu.models import rbcd
    from dpgo_tpu.utils.partition import partition_contiguous

    kw = {}
    if schedule is not None:
        kw["schedule"] = Schedule[schedule]
    if bf16:
        kw["solver"] = SolverParams(pallas_bf16_select=True)
    params = AgentParams(d=meas.d, r=r, num_robots=A, **kw)
    part = partition_contiguous(meas, A)
    graph, meta = rbcd.build_graph(part, r, dtype)
    X0 = rbcd.centralized_chordal_init(part, meta, graph, dtype)
    state = rbcd.init_state(graph, meta, X0, params=params)
    return state, graph, meta, params


def time_config(name, meas, A, r, rounds, schedule=None, trials=3,
                bf16=False):
    import jax.numpy as jnp
    from dpgo_tpu.models import rbcd

    state, graph, meta, params = build(meas, A, r, jnp.float32,
                                       schedule=schedule, bf16=bf16)
    form = rbcd._formulation(meta, params, graph)
    steps = lambda s, k: rbcd.rbcd_steps(s, graph, k, meta, params)
    t0 = time.perf_counter()
    st = steps(state, 1)
    _ = np.asarray(st.X)
    log(f"[{name}] form={form} n_max={meta.n_max} e_max={meta.e_max} "
        f"s_max={meta.s_max} compile {time.perf_counter()-t0:.1f}s")
    _ = np.asarray(steps(st, min(20, rounds)).X)  # warm
    rates = []
    for _ in range(trials):
        t0 = time.perf_counter()
        out = steps(state, rounds)
        _ = np.asarray(out.X)
        dt = time.perf_counter() - t0
        rates.append(rounds / dt)
        log(f"[{name}] {rounds / dt:.1f} rounds/s")
    log(f"[{name}] median {np.median(rates):.1f} rounds/s")
    return float(np.median(rates))


def sphere():
    from dpgo_tpu.utils.g2o import read_g2o
    meas = read_g2o(f"{DATA}/sphere2500.g2o")
    return time_config("sphere2500/8 r5", meas, 8, 5, 200)


def kitti():
    from dpgo_tpu.utils.g2o import read_g2o
    meas = read_g2o(f"{DATA}/kitti_00.g2o")
    return time_config("kitti00/16 r3 async", meas, 16, 3, 200,
                       schedule="ASYNC")


def city():
    from dpgo_tpu.utils.g2o import read_g2o
    meas = read_g2o(f"{DATA}/city10000.g2o")
    return time_config("city10000/32 r3", meas, 32, 3, 100)


def synth100k():
    from dpgo_tpu.utils.synthetic import make_measurements
    rng = np.random.default_rng(0)
    t0 = time.perf_counter()
    meas, _ = make_measurements(rng, n=100000, d=3, num_lc=20000,
                                rot_noise=0.01, trans_noise=0.01)
    log(f"[100k] synthesized in {time.perf_counter()-t0:.1f}s")
    time_config("100k/64 r5", meas, 64, 5, 20, trials=3)
    return time_config("100k/64 r5 bf16sel", meas, 64, 5, 20, trials=3,
                       bf16=True)


def ablate():
    """Break one sphere2500 fused round into pieces: exchange+gradient ELL
    pass vs the RTR kernel, plus kernel tCG stats."""
    import jax
    import jax.numpy as jnp
    from dpgo_tpu.models import rbcd
    from dpgo_tpu.ops import manifold, quadratic
    from dpgo_tpu.ops import pallas_tcg as ptcg
    from dpgo_tpu.utils.g2o import read_g2o

    meas = read_g2o(f"{DATA}/sphere2500.g2o")
    state, graph, meta, params = build(meas, 8, 5, jnp.float32)
    d, r = meta.d, meta.rank
    k = d + 1

    def grad_part(X):
        """Everything _rbcd_round does before the kernel: exchange + ELL
        gradient + S + chol transforms."""
        Z = rbcd.neighbor_buffer(rbcd.public_table(X, graph), graph)

        def one(x, z, e, s, m):
            buf = jnp.concatenate([x, z], axis=0)
            eg = quadratic.egrad_ell(buf, e, s, m)
            g = manifold.rgrad(x, eg)
            gn0 = manifold.norm(g)
            Y, GY = x[..., :d], eg[..., :d]
            M = jnp.einsum("nab,nac->nbc", Y, GY)
            S = 0.5 * (M + jnp.swapaxes(M, -1, -2))
            return g, gn0, S

        return jax.vmap(one)(X, Z, graph.edges, graph.inc_slot,
                             graph.inc_mask)

    @jax.jit
    def grad_rounds(X, n):
        def body(_, x):
            g, gn0, S = grad_part(x)
            return x + 0.0 * g  # keep the dependency
        return jax.lax.fori_loop(0, n, body, X)

    w = graph.edges.mask * graph.edges.weight
    nt, T = graph.eidx_i.shape[1], graph.eidx_i.shape[-1]
    wk = jax.vmap(lambda ww: ptcg.edge_tiles(ww, nt, T))(
        (w * graph.edges.kappa).astype(jnp.float32))
    wt = jax.vmap(lambda ww: ptcg.edge_tiles(ww, nt, T))(
        (w * graph.edges.tau).astype(jnp.float32))

    @jax.jit
    def kernel_rounds(X, n):
        g, gn0, S = grad_part(X)
        Z = rbcd.neighbor_buffer(rbcd.public_table(X, graph), graph)
        chol = state.chol
        Xc = jax.vmap(ptcg.comp_major)(X)
        Zc = jax.vmap(ptcg.comp_major)(Z)
        gc = jax.vmap(ptcg.comp_major)(g)
        Sc = jax.vmap(lambda s: s.transpose(1, 2, 0).reshape(d * d, -1))(S)
        Lc = jax.vmap(lambda c: c.transpose(1, 2, 0).reshape(k * k, -1))(chol)

        def body(_, xc):
            out, stats = jax.vmap(
                lambda ii, ij, rc, tc, wk1, wt1, xc1, zc1, sc1, lc1, gc1:
                ptcg.rtr_call(
                    ii, ij, rc, tc, wk1, wt1, xc1, zc1, sc1, lc1, gc1,
                    r=r, d=d, max_iters=params.solver.max_inner_iters,
                    kappa=params.solver.tcg_kappa,
                    theta=params.solver.tcg_theta,
                    initial_radius=params.solver.initial_radius,
                    max_rejections=params.solver.max_rejections))(
                graph.eidx_i, graph.eidx_j, graph.rot_t, graph.trn_t,
                wk, wt, xc, Zc, Sc, Lc, gc)
            return out
        return jax.lax.fori_loop(0, n, body, Xc), None

    N = 200
    # full round reference
    steps = lambda s, n: rbcd.rbcd_steps(s, graph, n, meta, params)
    _ = np.asarray(steps(state, 1).X)
    _ = np.asarray(steps(state, 50).X)
    t0 = time.perf_counter()
    _ = np.asarray(steps(state, N).X)
    t_full = time.perf_counter() - t0
    log(f"[ablate] full round: {t_full/N*1e3:.3f} ms/round "
        f"({N/t_full:.0f} r/s)")

    X = state.X
    _ = np.asarray(grad_rounds(X, 1))
    t0 = time.perf_counter()
    _ = np.asarray(grad_rounds(X, N))
    t_grad = time.perf_counter() - t0
    log(f"[ablate] exchange+grad only: {t_grad/N*1e3:.3f} ms/round")

    out, _ = kernel_rounds(X, 1)
    _ = np.asarray(out)
    t0 = time.perf_counter()
    out, _ = kernel_rounds(X, N)
    _ = np.asarray(out)
    t_kern = time.perf_counter() - t0
    log(f"[ablate] grad+kernel (no schedule/status): "
        f"{t_kern/N*1e3:.3f} ms/round")

    # kernel stats from one un-fused call
    g, gn0, S = grad_part(X)
    Z = rbcd.neighbor_buffer(rbcd.public_table(X, graph), graph)
    Xc = jax.vmap(ptcg.comp_major)(X)
    Zc = jax.vmap(ptcg.comp_major)(Z)
    gc = jax.vmap(ptcg.comp_major)(g)
    Sc = jax.vmap(lambda s: s.transpose(1, 2, 0).reshape(d * d, -1))(S)
    Lc = jax.vmap(lambda c: c.transpose(1, 2, 0).reshape(k * k, -1))(
        state.chol)
    _, stats = jax.vmap(
        lambda ii, ij, rc, tc, wk1, wt1, xc1, zc1, sc1, lc1, gc1:
        ptcg.rtr_call(
            ii, ij, rc, tc, wk1, wt1, xc1, zc1, sc1, lc1, gc1,
            r=r, d=d, max_iters=params.solver.max_inner_iters,
            kappa=params.solver.tcg_kappa, theta=params.solver.tcg_theta,
            initial_radius=params.solver.initial_radius,
            max_rejections=params.solver.max_rejections))(
        graph.eidx_i, graph.eidx_j, graph.rot_t, graph.trn_t,
        wk, wt, Xc, Zc, Sc, Lc, gc)
    log(f"[ablate] kernel stats per agent (attempts, accepted, f0, f): "
        f"{np.asarray(stats).squeeze()}")




def ais():
    from dpgo_tpu.utils.g2o import read_g2o
    meas = read_g2o(f"{DATA}/ais2klinik.g2o")
    time_config("ais2klinik/32 r3 colored", meas, 32, 3, 200,
                schedule="COLORED")
    # Monotonicity check on TPU: 50 colored sweeps (C rounds each).
    import jax.numpy as jnp
    from dpgo_tpu.models import rbcd
    from dpgo_tpu.ops import quadratic
    from dpgo_tpu.types import edge_set_from_measurements
    from dpgo_tpu.utils.partition import partition_contiguous

    state, graph, meta, params = build(meas, 32, 3, jnp.float32,
                                       schedule="COLORED")
    part = partition_contiguous(meas, 32)  # deterministic: same as build()
    edges_g = edge_set_from_measurements(part.meas_global, dtype=jnp.float32)
    costs = []
    for _ in range(50):
        state = rbcd.rbcd_steps(state, graph, meta.num_colors, meta, params)
        costs.append(float(quadratic.cost(
            rbcd.gather_to_global(state.X, graph, meas.num_poses), edges_g)))
    # f32-relative tolerance: absolute 1e-3 sits below rounding noise at
    # cost magnitudes ~1e5
    inc = sum(1 for a, b in zip(costs, costs[1:])
              if b > a + 1e-6 * max(abs(a), 1.0))
    log(f"[ais colored] C={meta.num_colors} f0={costs[0]:.0f} "
        f"f_end={costs[-1]:.0f} increases={inc}")


def ais_gnc():
    """Config #4 second dataset with the round-3 kernel + COLORED."""
    import time as _t
    import jax.numpy as jnp
    from dpgo_tpu.config import AgentParams, RobustCostParams, \
        RobustCostType, Schedule
    from dpgo_tpu.models import rbcd
    from dpgo_tpu.utils.g2o import read_g2o
    from dpgo_tpu.utils.partition import partition_contiguous

    meas = read_g2o(f"{DATA}/ais2klinik.g2o")
    params = AgentParams(
        d=2, r=3, num_robots=32, schedule=Schedule.COLORED,
        rel_change_tol=0.0,
        robust=RobustCostParams(cost_type=RobustCostType.GNC_TLS))
    part = partition_contiguous(meas, 32)
    t0 = _t.perf_counter()
    res = rbcd.solve_rbcd(meas, 32, params=params, max_iters=1500,
                          grad_norm_tol=0.5, eval_every=50,
                          dtype=jnp.float32, part=part)
    dt = _t.perf_counter() - t0
    inc = sum(1 for a, b in zip(res.cost_history, res.cost_history[1:])
              if b > a + 1e-3)
    rej = float((np.asarray(res.weights) < 0.5).sum())
    log(f"[ais gnc colored] {res.iterations} rounds in {dt:.1f}s "
        f"({res.iterations/dt:.0f} r/s incl. compile+evals), cost "
        f"{res.cost_history[0]:.0f} -> {res.cost_history[-1]:.0f}, "
        f"increases={inc}, rejected_edges={rej:.0f}, "
        f"terminated={res.terminated_by}")


if __name__ == "__main__":
    which = sys.argv[1:] or ["sphere", "ablate"]
    for w in which:
        {"sphere": sphere, "kitti": kitti, "city": city,
         "100k": synth100k, "ablate": ablate, "ais": ais,
         "ais_gnc": ais_gnc}[w]()
