"""A/B the kernel selection-matmul modes at the 100k/64 shape
(VERDICT r3 item 5: attack the selection-matmul ceiling).

Modes (``config.SolverParams.pallas_sel_mode``):
* f32    — Precision.HIGHEST one-hot matmuls (~6 emulated bf16 passes)
* bf16x3 — 3-pass hi/mid/lo split, covers the full 24-bit f32 mantissa:
           f32-grade numerics at half the pass count
* bf16   — 2-pass hi/lo split (~2^-16 error), the round-3 opt-in mode

Also numerics: 100-round cost trajectories per mode vs the f32 arm.

Usage: python experiments/selmode_100k.py [rounds] [--sphere]
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, "/root/repo")


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def build(meas, A, r, mode):
    import jax.numpy as jnp
    from dpgo_tpu.config import AgentParams, SolverParams
    from dpgo_tpu.models import rbcd
    from dpgo_tpu.utils.partition import partition_contiguous

    params = AgentParams(d=meas.d, r=r, num_robots=A,
                         solver=SolverParams(pallas_sel_mode=mode))
    part = partition_contiguous(meas, A)
    graph, meta = rbcd.build_graph(part, r, jnp.float32, sel_mode=mode)
    X0 = rbcd.centralized_chordal_init(part, meta, graph, jnp.float32)
    state = rbcd.init_state(graph, meta, X0, params=params)
    return state, graph, meta, params


def measure(meas, A, r, mode, rounds, trials=3):
    from dpgo_tpu.models import rbcd

    state, graph, meta, params = build(meas, A, r, mode)
    form = rbcd._formulation(meta, params, graph)
    assert form == "pallas", f"{mode}: formulation resolved to {form}"
    steps = lambda s, k: rbcd.rbcd_steps(s, graph, k, meta, params)
    t0 = time.perf_counter()
    st = steps(state, 1)
    _ = np.asarray(st.X)
    log(f"[{mode}] compile {time.perf_counter()-t0:.1f}s "
        f"(n_max={meta.n_max} e_max={meta.e_max})")
    _ = np.asarray(steps(st, min(20, rounds)).X)
    rates = []
    for _ in range(trials):
        t0 = time.perf_counter()
        out = steps(state, rounds)
        _ = np.asarray(out.X)
        rates.append(rounds / (time.perf_counter() - t0))
        log(f"[{mode}] {rates[-1]:.1f} rounds/s")
    # Numerics: 100-round final cost vs mode-f32 computed by caller.
    st100 = steps(state, 100)
    Xh = np.asarray(st100.X)
    return float(np.median(rates)), Xh


def main():
    args = [a for a in sys.argv[1:] if not a.startswith("-")]
    rounds = int(args[0]) if args else 60
    from dpgo_tpu.utils.g2o import read_g2o
    from dpgo_tpu.utils.synthetic import make_measurements

    if "--sphere" in sys.argv:
        meas = read_g2o("/root/reference/data/sphere2500.g2o")
        A, r, name = 8, 5, "sphere2500/8"
    else:
        t0 = time.perf_counter()
        rng = np.random.default_rng(0)
        meas, _ = make_measurements(rng, n=100000, d=3, num_lc=20000,
                                    rot_noise=0.01, trans_noise=0.01)
        A, r, name = 64, 5, "100k/64"
        log(f"synthesized 100k in {time.perf_counter()-t0:.1f}s")

    out = {"config": name, "rounds": rounds}
    X_ref = None
    for mode in ("f32", "bf16x3", "bf16"):
        rate, Xh = measure(meas, A, r, mode, rounds)
        if X_ref is None:
            X_ref = Xh
            drift = 0.0
        else:
            drift = float(np.abs(Xh - X_ref).max())
        out[mode] = {"rounds_per_s": round(rate, 2),
                     "x_drift_vs_f32_at_100r": drift}
        log(f"[{mode}] median {rate:.1f} rounds/s, "
            f"100-round iterate drift vs f32: {drift:.2e}")
    out["speedup_bf16x3_vs_f32"] = round(
        out["bf16x3"]["rounds_per_s"] / out["f32"]["rounds_per_s"], 3)
    print(json.dumps(out))
    with open(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "selmode_results.json"), "a") as f:
        f.write(json.dumps(out) + "\n")


if __name__ == "__main__":
    main()
