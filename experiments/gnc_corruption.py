"""Corrupted-data GNC robustness benchmark (VERDICT r3 item 3).

Protocol (the GNC-paper one, via ``utils.synthetic.corrupt_loop_closures``):
inject 10/20/40% random gross-outlier loop closures into sphere2500 and
city10000, run the robust GNC_TLS pipeline on the default backend (TPU),
and report

* edge-rejection precision / recall against the injected ground truth,
* the final iterate's cost on the CLEAN (pre-corruption) edge set,
  relative to the outlier-free optimum f* (centralized f64 solve, cached),
* wall clock and rounds.

This is the first at-scale demonstration that the GNC machinery
(reference ``src/DPGO_robust.cpp:23-103``, ``src/PGOAgent.cpp:1181-1245``)
does its actual job — the reference repo ships no corrupted datasets and
its shipped benchmarks are outlier-free (city10000's weights all converge
to 1; BASELINE.md round-2 table).

Usage: python experiments/gnc_corruption.py [--quick]
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import numpy as np

sys.path.insert(0, "/root/repo")

DATA = "/root/reference/data"
CACHE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     ".fopt_cache.json")
# Cache-key protocol version: bump whenever the f* solve recipe or the
# corruption protocol changes, so stale cached optima cannot silently
# skew reported gaps (ADVICE r4).  v1 = solve_local gn<=1e-7 +
# corrupt_loop_closures as of round 4.
FOPT_KEY_VERSION = 1

# (file, agents, rank, rounds) — 3000 rounds = 100 GNC weight updates at
# the default inner_iters=30, the reference's full annealing budget
# (gnc_max_iters, DPGO_robust.h:48-55), plus post-freeze descent.
CONFIGS = [
    ("sphere2500.g2o", 8, 5, 3000),
    ("city10000.g2o", 32, 3, 3000),
]
FRACTIONS = [0.1, 0.2, 0.4]


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def fopt_inliers(fname: str, rank: int, fraction: float, seed: int = 0,
                 mode: str = "random") -> float:
    """Optimum f* of the INLIER-ONLY subproblem (odometry + uncorrupted
    loop closures) via a centralized f64 CPU solve, cached per
    (dataset, rank, fraction, seed).

    This is the honest comparator for a robust run: the corrupted problem
    never contains the true versions of the corrupted edges, so the final
    iterate can only be judged on the edges GNC was supposed to keep.
    Runs in a subprocess because the TPU-tunnel process cannot enable x64
    (see bench.py).
    """
    cache = {}
    if os.path.exists(CACHE):
        with open(CACHE) as f:
            cache = json.load(f)
    mode_tag = "" if mode == "random" else f"_{mode}"
    key = f"{fname}_r{rank}_p{fraction}_s{seed}{mode_tag}_v{FOPT_KEY_VERSION}"
    legacy = f"{fname}_r{rank}_p{fraction}_s{seed}"
    v1key = f"{legacy}_v1"
    if legacy in cache and v1key not in cache:  # pre-versioning entry = v1
        cache[v1key] = cache.pop(legacy)
        with open(CACHE, "w") as f:
            json.dump(cache, f)
    if key in cache:
        return cache[key]
    code = f"""
import jax, json, numpy as np
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)
import jax.numpy as jnp
from dpgo_tpu.models.local_pgo import solve_local
from dpgo_tpu.utils.g2o import read_g2o
from dpgo_tpu.utils.synthetic import (corrupt_loop_closures,
                                      corrupt_loop_closures_correlated)
meas = read_g2o({f"{DATA}/{fname}"!r})
fn = corrupt_loop_closures_correlated if {mode!r} == "correlated" \
    else corrupt_loop_closures
_, idx = fn(meas, {fraction}, seed={seed})
keep = np.ones(len(meas), bool); keep[idx] = False
res = solve_local(meas.select(keep), rank={rank}, grad_norm_tol=1e-7,
                  max_iters=3000, dtype=jnp.float64)
print(json.dumps({{"f": float(res.cost), "gn": float(res.grad_norm)}}))
"""
    out = subprocess.run([sys.executable, "-c", code],
                         env=dict(os.environ, PYTHONPATH="/root/repo"),
                         capture_output=True, text=True, timeout=7200)
    if out.returncode != 0:
        raise RuntimeError(f"f* solve failed:\n{out.stderr[-2000:]}")
    d = json.loads(out.stdout.strip().splitlines()[-1])
    log(f"  [{fname} p={fraction}] inlier f* = {d['f']:.7f} "
        f"(gradnorm {d['gn']:.1e})")
    cache[key] = d["f"]
    with open(CACHE, "w") as f:
        json.dump(cache, f)
    return d["f"]


def run_one(fname: str, A: int, r: int, rounds: int, fraction: float,
            seed: int = 0, mode: str = "random", passes: int = 3):
    import jax
    import jax.numpy as jnp
    from dpgo_tpu.config import (AgentParams, RobustCostParams,
                                 RobustCostType, Schedule)
    from dpgo_tpu.models import rbcd
    from dpgo_tpu.ops import quadratic
    from dpgo_tpu.types import edge_set_from_measurements
    from dpgo_tpu.utils.g2o import read_g2o
    from dpgo_tpu.utils.partition import partition_contiguous
    from dpgo_tpu.utils.synthetic import (corrupt_loop_closures,
                                          corrupt_loop_closures_correlated,
                                          rejection_scores)

    dtype = jnp.float32 if jax.devices()[0].platform != "cpu" else jnp.float64
    clean = read_g2o(f"{DATA}/{fname}")
    corrupt_fn = corrupt_loop_closures_correlated if mode == "correlated" \
        else corrupt_loop_closures
    meas, outlier_idx = corrupt_fn(clean, fraction, seed=seed)

    params = AgentParams(
        d=clean.d, r=r, num_robots=A, schedule=Schedule.COLORED,
        robust=RobustCostParams(cost_type=RobustCostType.GNC_TLS),
        rel_change_tol=0.0, acceleration=True, restart_interval=100,
    )
    t0 = time.perf_counter()
    # Iterated (3-pass) GNC: anneal, hard-drop rejected LCs, re-anneal —
    # a single pass at BCD inner-convergence leaves a few gross outliers
    # above the rejection threshold, and they bend the whole solution
    # (see solve_rbcd_robust_iterated's docstring for the measurement);
    # pass boundaries also REINSTATE wrongly-dropped edges whose residual
    # at the cleaner iterate re-enters the TLS inlier band (the 40%
    # over-rejection fix).  Init is chordal, not odometry: the iterated
    # anneal recovers from a corruption-poisoned chordal basin, while
    # city10000's odometry drift is unrecoverable (A/B in
    # centralized_odometry_init's docstring).
    res, w, kept = rbcd.solve_rbcd_robust_iterated(
        meas, A, params, passes=passes, max_iters=rounds, grad_norm_tol=0.0,
        eval_every=rounds // 4, dtype=dtype)
    wall = time.perf_counter() - t0

    from dpgo_tpu.types import loop_closure_mask
    prec, rec, n_rej = rejection_scores(w, meas, outlier_idx)
    lc = loop_closure_mask(meas)
    conv = float(np.mean((w[lc] < 1e-3) | (w[lc] > 1 - 1e-3)))
    # Final cost on the INLIER-ONLY edge set (odometry + uncorrupted LCs) —
    # the edges GNC was supposed to keep; compared against that
    # subproblem's own f64 optimum by the caller.
    keep = np.ones(len(meas), bool)
    keep[outlier_idx] = False
    edges_in = edge_set_from_measurements(clean.select(keep), dtype=dtype)
    # res.X lives on the LAST pass's (filtered) graph; poses are unchanged
    # by filtering, but rebuild that graph for the gather.
    part = partition_contiguous(meas.select(kept), A)
    graph, meta = rbcd.build_graph(part, r, dtype)
    Xg = rbcd.gather_to_global(res.X, graph, clean.num_poses)
    f_in = float(quadratic.cost(jnp.asarray(Xg), edges_in))
    return dict(dataset=fname, mode=mode, fraction=fraction,
                n_lc_out=len(outlier_idx),
                precision=prec, recall=rec, n_rejected=n_rej,
                weight_converged_ratio=conv, f_inlier=f_in,
                rounds=res.iterations, wall=wall,
                cost_final=float(res.cost_history[-1]))


def main():
    quick = "--quick" in sys.argv
    # Correlated (perceptual-aliasing) mode: clusters of mutually
    # consistent false loop closures at 10-25% (VERDICT r4 item 4);
    # default remains the literature's random gross-outlier protocol.
    mode = "correlated" if "--correlated" in sys.argv else "random"
    fractions = [0.1, 0.15, 0.25] if mode == "correlated" else FRACTIONS
    # --passes N: A/B the iterated-GNC pass count.  Between-pass
    # reinstatement is the 40%-random-corruption fix, but a mutually
    # consistent aliasing cluster can pass the residual re-test once the
    # iterate has bent toward it — passes=1 measures that mechanism.
    passes = 3
    if "--passes" in sys.argv:
        passes = int(sys.argv[sys.argv.index("--passes") + 1])
    rows = []
    for fname, A, r, rounds in CONFIGS:
        if quick and fname != "sphere2500.g2o":
            continue
        for frac in ([0.2] if quick else fractions):
            row = run_one(fname, A, r, rounds if not quick else 300, frac,
                          mode=mode, passes=passes)
            row["passes"] = passes
            fstar = fopt_inliers(fname, r, frac, mode=mode)
            row["f_star_inlier"] = fstar
            row["rel_excess"] = row["f_inlier"] / fstar - 1.0
            rows.append(row)
            log(f"[{fname} {mode} {int(frac*100)}%] rejected {row['n_rejected']} "
                f"(injected {row['n_lc_out']}): precision {row['precision']:.3f} "
                f"recall {row['recall']:.3f} conv {row['weight_converged_ratio']:.2f}; "
                f"inlier-edge cost {row['f_inlier']:.2f} "
                f"vs f*_in {fstar:.2f} (+{row['rel_excess']*100:.2f}%), "
                f"{row['rounds']} rounds in {row['wall']:.1f}s")

    print("\n| dataset | outliers | rejected | precision | recall | "
          "inlier cost vs f*_in | rounds | wall |")
    print("|---|---|---|---|---|---|---|---|")
    for w in rows:
        print(f"| {w['dataset'].replace('.g2o','')} | {int(w['fraction']*100)}% "
              f"({w['n_lc_out']}) | {w['n_rejected']} | {w['precision']:.3f} | "
              f"{w['recall']:.3f} | +{w['rel_excess']*100:.2f}% | "
              f"{w['rounds']} | {w['wall']:.1f}s |")
    # Merge by (dataset, mode, fraction) so the random and correlated
    # sweeps accumulate into one results file.
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "gnc_corruption_results.json")
    merged = {}
    if os.path.exists(path):
        with open(path) as f:
            for old in json.load(f):
                merged[(old["dataset"], old.get("mode", "random"),
                        old["fraction"], old.get("passes", 3))] = old
    for w in rows:
        merged[(w["dataset"], w["mode"], w["fraction"],
                w.get("passes", 3))] = w
    with open(path, "w") as f:
        json.dump(list(merged.values()), f, indent=1)


if __name__ == "__main__":
    main()
