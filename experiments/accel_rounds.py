"""Rounds-to-gap on sphere2500/8 r=5: acceleration on vs off (CPU f64 —
round counts are backend-independent; wall-clock is measured on TPU later).
"""
import os, sys, time
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)
import jax.numpy as jnp
import numpy as np
from dpgo_tpu.config import AgentParams, SolverParams
from dpgo_tpu.models import rbcd
from dpgo_tpu.ops import quadratic
from dpgo_tpu.types import edge_set_from_measurements
from dpgo_tpu.utils.g2o import read_g2o
from dpgo_tpu.utils.partition import partition_contiguous

F_OPT = 843.5029071  # certified f* (bench_convergence cache)
meas = read_g2o("/root/reference/data/sphere2500.g2o")
part = partition_contiguous(meas, 8)
edges_g = edge_set_from_measurements(part.meas_global, dtype=jnp.float64)
n_total = part.meas_global.num_poses

for accel, ri in [(False, 30), (True, 30), (True, 60), (True, 100)]:
    params = AgentParams(d=3, r=5, num_robots=8, rel_change_tol=0.0,
                         acceleration=accel, restart_interval=ri,
                         solver=SolverParams(grad_norm_tol=1e-9,
                                             max_inner_iters=10))
    graph, meta = rbcd.build_graph(part, 5, jnp.float64)
    X0 = rbcd.centralized_chordal_init(part, meta, graph, jnp.float64)
    state = rbcd.init_state(graph, meta, X0, params=params)

    @jax.jit
    def cost_of(s):
        return quadratic.cost(rbcd.gather_to_global(s.X, graph, n_total),
                              edges_g)

    ladder = [1e-3, 1e-4, 1e-5, 1e-6]
    crossed = {}
    it = 0
    while it < 800 and len(crossed) < len(ladder):
        # step 5 rounds, honoring restart flags
        for _ in range(5):
            restart = accel and (it + 1) % ri == 0
            state = rbcd.rbcd_step(state, graph, meta, params,
                                   update_weights=False, restart=restart)
            it += 1
        f = float(cost_of(state))
        for g in ladder:
            if g not in crossed and f <= F_OPT * (1 + g):
                crossed[g] = it
    print(f"accel={accel} restart={ri}: " +
          ", ".join(f"{g:.0e}@{crossed.get(g, '>800')}" for g in ladder),
          flush=True)
