"""At-scale rank escape (VERDICT r4 item 2): a 100k-pose dataset with a
certifiably suboptimal starting-rank critical point, run through the
sharded staircase on TPU: descent -> certificate FAIL -> saddle escape ->
re-certify at the higher rank.

Dataset: ``utils.synthetic.make_stitched_winding(1000, 100)`` — 1000
identity-measurement cycles of length 100 stitched by weak bridges
(100,000 poses, 101k edges); the wound configuration is an exactly
critical, strictly suboptimal rank-2 local minimum (see the generator's
docstring and tests/test_staircase_escape_stitched.py).  The round-4
staircase only ever certified at its starting rank (the 100k synthetic's
relaxation is tight); this dataset makes the OTHER half of the
staircase's job — fail, escape, re-certify — measurable at benchmark
scale.  No reference anchor exists: certification is absent from the
reference codebase (SURVEY.md section 7 / M6).

Usage: python experiments/staircase_escape_100k.py [rounds_per_rank]
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, "/root/repo")


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def main():
    import jax
    import jax.numpy as jnp
    from dpgo_tpu.models import rbcd
    from dpgo_tpu.parallel import certify as dcert
    from dpgo_tpu.utils.partition import partition_contiguous
    from dpgo_tpu.utils.synthetic import make_stitched_winding

    rounds = int(sys.argv[1]) if len(sys.argv) > 1 else 2000
    n_cycles, cycle_len = 1000, 100
    log(f"generating stitched-winding dataset: {n_cycles} x {cycle_len} "
        f"= {n_cycles * cycle_len} poses ...")
    meas, Xw = make_stitched_winding(n_cycles, cycle_len)
    dev = jax.devices()[0]
    log(f"device: {dev.platform} ({dev.device_kind}); staircase r=2->5, "
        f"{rounds} rounds/rank, 64 agents, wound init")

    part = partition_contiguous(meas, 64)
    graph, meta = rbcd.build_graph(part, 2, jnp.float32)
    Xa0 = np.asarray(rbcd.scatter_to_agents(jnp.asarray(Xw, jnp.float32),
                                            graph))

    t0 = time.perf_counter()
    # eta 3e-5: tol = eta * weight_scale = 3e-4 — still at the per-edge
    # weight scale (relative to sigma ~170 it is ~2e-6, nothing like the
    # vacuous eta*sigma rule), sized so a CONVERGED f64 eigenpair with a
    # ~1e-4 residual at 300k dims can clear the two-sided decision.
    T, Xa, rank, cert, hist = dcert.solve_staircase_sharded(
        meas, 64, r_min=2, r_max=5, rounds_per_rank=rounds,
        X0=Xa0, accel=True, eta=3e-5, verbose=True)
    total = time.perf_counter() - t0

    rows = [dict(rank=r, cost=f, lambda_min=lam, wall_s=w)
            for r, f, lam, w in hist]
    out = dict(metric="staircase_escape_100k_64agents",
               dataset=f"stitched_winding_{n_cycles}x{cycle_len}",
               certified=bool(cert.certified), final_rank=rank,
               lambda_min_final=cert.lambda_min,
               tol_final=cert.tol, decidable=cert.decidable,
               lambda_min_f64=cert.lambda_min_f64,
               total_s=round(total, 1), per_rank=rows)
    log(f"final rank {rank}, certified={cert.certified}, "
        f"total {total:.1f}s")
    print(json.dumps(out))
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "staircase_escape_100k_results.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=1)


if __name__ == "__main__":
    main()
